"""Experiment tracking: a native sqlite store, MLflow-schema-compatible.

The reference logs through MLflow onto ``sqlite:///coda.sqlite`` with the
hierarchy experiment = task -> parent run = method -> child run = seed
(reference ``main.py:15-17,131-168``), and its downstream analysis bypasses
the MLflow API entirely, issuing raw SQL over the sqlite schema — joining
``metrics ⋈ runs ⋈ experiments ⋈ tags`` on the ``mlflow.parentRunId`` /
``mlflow.runName`` tags (reference ``paper/tab1.py:28-51``).

This module implements that schema subset directly (no MLflow dependency —
it is not installed in TPU images), so:
  * the reference's own analysis SQL runs unchanged against our DB;
  * metric series emerge from the compiled scan as whole arrays and are
    written in one executemany batch per run, not one row-trip per step.

Concurrency: sqlite in WAL mode with a busy timeout — multiple benchmark
processes (the sweep engine's analog of the reference's SLURM fan-out) can
log to one DB, which is exactly the concurrency control the reference
delegates to MLflow.

MLflow-client compatibility: this store implements the tables the analysis
SQL joins on (experiments/runs/metrics/params/tags) plus ``latest_metrics``,
but NOT MLflow's alembic version bookkeeping — so pointing ``mlflow ui``
directly at this file will trigger its schema-version check. The supported
path to the real UI is ``scripts/export_mlflow.py``, which replays the store
through the genuine MLflow client API into a fresh MLflow-owned DB
(round-trip covered by ``tests/test_mlflow_compat.py``, skipped where mlflow
isn't installed — it is not in TPU images).
"""

from __future__ import annotations

import os
import sqlite3
import time
import uuid
from typing import Iterable, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS experiments (
    experiment_id    INTEGER PRIMARY KEY AUTOINCREMENT,
    name             TEXT UNIQUE NOT NULL,
    artifact_location TEXT,
    lifecycle_stage  TEXT DEFAULT 'active',
    creation_time    INTEGER,
    last_update_time INTEGER
);
CREATE TABLE IF NOT EXISTS runs (
    run_uuid         TEXT PRIMARY KEY,
    name             TEXT,
    source_type      TEXT,
    source_name      TEXT,
    entry_point_name TEXT,
    user_id          TEXT,
    status           TEXT,
    start_time       INTEGER,
    end_time         INTEGER,
    source_version   TEXT,
    lifecycle_stage  TEXT DEFAULT 'active',
    artifact_uri     TEXT,
    experiment_id    INTEGER,
    deleted_time     INTEGER
);
CREATE TABLE IF NOT EXISTS metrics (
    key       TEXT NOT NULL,
    value     REAL NOT NULL,
    timestamp INTEGER NOT NULL,
    run_uuid  TEXT NOT NULL,
    step      INTEGER DEFAULT 0,
    is_nan    INTEGER DEFAULT 0,
    PRIMARY KEY (key, timestamp, step, run_uuid, value, is_nan)
);
CREATE TABLE IF NOT EXISTS params (
    key      TEXT NOT NULL,
    value    TEXT NOT NULL,
    run_uuid TEXT NOT NULL,
    PRIMARY KEY (key, run_uuid)
);
CREATE TABLE IF NOT EXISTS tags (
    key      TEXT NOT NULL,
    value    TEXT,
    run_uuid TEXT NOT NULL,
    PRIMARY KEY (key, run_uuid)
);
CREATE TABLE IF NOT EXISTS latest_metrics (
    key       TEXT NOT NULL,
    value     REAL NOT NULL,
    timestamp INTEGER,
    step      INTEGER NOT NULL,
    is_nan    INTEGER NOT NULL,
    run_uuid  TEXT NOT NULL,
    PRIMARY KEY (key, run_uuid)
);
CREATE INDEX IF NOT EXISTS idx_metrics_run ON metrics(run_uuid);
CREATE INDEX IF NOT EXISTS idx_runs_experiment ON runs(experiment_id);
"""


def _now_ms() -> int:
    # wall-clock: MLflow-schema timestamp columns are epoch ms (a timestamp)
    return int(time.time() * 1000)


class Run:
    """An open tracking run; log params/metrics, then close (or use `with`)."""

    def __init__(self, store: "TrackingStore", run_uuid: str):
        self.store = store
        self.run_uuid = run_uuid

    def log_param(self, key: str, value) -> None:
        self.store._conn.execute(
            "INSERT OR REPLACE INTO params (key, value, run_uuid) VALUES (?,?,?)",
            (str(key), str(value), self.run_uuid),
        )

    def log_params(self, params: dict) -> None:
        self.store._conn.executemany(
            "INSERT OR REPLACE INTO params (key, value, run_uuid) VALUES (?,?,?)",
            [(str(k), str(v), self.run_uuid) for k, v in params.items()],
        )

    def set_tag(self, key: str, value) -> None:
        self.store._conn.execute(
            "INSERT OR REPLACE INTO tags (key, value, run_uuid) VALUES (?,?,?)",
            (str(key), str(value), self.run_uuid),
        )

    def log_metric(self, key: str, value: float, step: int = 0) -> None:
        self.log_metric_series(key, [value], start_step=step)

    def log_metric_series(
        self, key: str, values: Iterable[float], start_step: int = 1
    ) -> None:
        """Batch-insert a whole per-step series (one executemany)."""
        self.log_metric_points(
            key, [(start_step + i, v) for i, v in enumerate(values)])

    def log_metric_points(self, key: str, points: Iterable[tuple]) -> None:
        """Batch-insert explicit ``(step, value)`` points.

        Re-logging a step replaces the old row (the PRIMARY KEY includes the
        timestamp, so INSERT OR REPLACE alone would duplicate on rerun —
        e.g. ``--force-rerun`` of a reused seed run).
        """
        ts = _now_ms()
        # sqlite binds float('nan') as NULL which violates NOT NULL; store
        # 0.0 with is_nan=1 instead (MLflow's own convention)
        rows = []
        for i, (step, v) in enumerate(points):
            v = float(v)
            is_nan = v != v
            rows.append((key, 0.0 if is_nan else v, ts + i, self.run_uuid,
                         int(step), int(is_nan)))
        if not rows:
            return
        self.store._conn.executemany(
            "DELETE FROM metrics WHERE run_uuid=? AND key=? AND step=?",
            [(self.run_uuid, key, r[4]) for r in rows],
        )
        self.store._conn.executemany(
            "INSERT INTO metrics (key, value, timestamp, run_uuid,"
            " step, is_nan) VALUES (?,?,?,?,?,?)",
            rows,
        )
        # maintain MLflow's latest_metrics (max-step row per key; what the
        # MLflow UI's run table reads)
        last = max(rows, key=lambda r: r[4])
        self.store._conn.execute(
            "INSERT INTO latest_metrics (key, value, timestamp, step,"
            " is_nan, run_uuid) VALUES (?,?,?,?,?,?)"
            " ON CONFLICT(key, run_uuid) DO UPDATE SET"
            " value=excluded.value, timestamp=excluded.timestamp,"
            " step=excluded.step, is_nan=excluded.is_nan"
            " WHERE excluded.step >= latest_metrics.step",
            (key, last[1], last[2], last[4], last[5], self.run_uuid),
        )

    def log_artifact_bytes(self, name: str, data: bytes) -> str:
        """Write ``data`` under this run's artifact dir; returns the path.

        The artifact dir is ``<db>_artifacts/<run_uuid>/`` and is recorded in
        the run's ``artifact_uri`` column (the MLflow convention the
        reference's consumers expect to exist, reference ``main.py:101-103``
        under ``_DEBUG_VIZ``).
        """
        import os

        d = os.path.join(self.store.artifact_root, self.run_uuid)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, name)
        with open(path, "wb") as f:
            f.write(data)
        self.store._conn.execute(
            "UPDATE runs SET artifact_uri=? WHERE run_uuid=?",
            (d, self.run_uuid),
        )
        return path

    def log_figure(self, name: str, fig) -> str:
        """Rasterize a matplotlib figure and log it as a PNG artifact."""
        from coda_tpu.utils.viz import fig_to_png

        if not name.endswith(".png"):
            name += ".png"
        return self.log_artifact_bytes(name, fig_to_png(fig))

    def finish(self, status: str = "FINISHED") -> None:
        self.store._conn.execute(
            "UPDATE runs SET status=?, end_time=? WHERE run_uuid=?",
            (status, _now_ms(), self.run_uuid),
        )
        self.store._conn.commit()

    def __enter__(self) -> "Run":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish("FINISHED" if exc_type is None else "FAILED")


class TrackingStore:
    """MLflow-schema sqlite store (see module docstring)."""

    def __init__(self, db_path: str = "coda.sqlite"):
        self.db_path = db_path
        self.artifact_root = db_path + "_artifacts"
        parent = os.path.dirname(os.path.abspath(db_path))
        os.makedirs(parent, exist_ok=True)
        self._conn = sqlite3.connect(db_path, timeout=60.0)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA busy_timeout=60000")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    # -- experiments -------------------------------------------------------
    def get_or_create_experiment(self, name: str) -> int:
        row = self._conn.execute(
            "SELECT experiment_id FROM experiments WHERE name=?", (name,)
        ).fetchone()
        if row:
            return row[0]
        now = _now_ms()
        cur = self._conn.execute(
            "INSERT INTO experiments (name, lifecycle_stage, creation_time,"
            " last_update_time) VALUES (?, 'active', ?, ?)",
            (name, now, now),
        )
        self._conn.commit()
        return cur.lastrowid

    # -- runs --------------------------------------------------------------
    def find_run(self, experiment: str, run_name: str) -> Optional[tuple[str, str]]:
        """Return (run_uuid, status) of the run with this name tag, if any."""
        row = self._conn.execute(
            """SELECT r.run_uuid, r.status FROM runs r
               JOIN experiments e ON r.experiment_id = e.experiment_id
               JOIN tags t ON t.run_uuid = r.run_uuid AND t.key='mlflow.runName'
               WHERE e.name=? AND t.value=? AND r.lifecycle_stage='active'
               ORDER BY r.start_time DESC LIMIT 1""",
            (experiment, run_name),
        ).fetchone()
        return (row[0], row[1]) if row else None

    def is_finished(self, experiment: str, run_name: str) -> bool:
        found = self.find_run(experiment, run_name)
        return bool(found and found[1] == "FINISHED")

    def run(
        self,
        experiment: str,
        run_name: str,
        parent: Optional[Run] = None,
        params: Optional[dict] = None,
        reuse: bool = True,
    ) -> Run:
        """Open (or resume) a named run. Usable as a context manager."""
        exp_id = self.get_or_create_experiment(experiment)
        existing = self.find_run(experiment, run_name) if reuse else None
        if existing:
            run_uuid = existing[0]
            self._conn.execute(
                "UPDATE runs SET status='RUNNING' WHERE run_uuid=?", (run_uuid,)
            )
        else:
            run_uuid = uuid.uuid4().hex
            self._conn.execute(
                "INSERT INTO runs (run_uuid, name, status, start_time,"
                " lifecycle_stage, experiment_id, user_id) VALUES"
                " (?, ?, 'RUNNING', ?, 'active', ?, ?)",
                (run_uuid, run_name, _now_ms(), exp_id,
                 os.environ.get("USER", "coda")),
            )
        r = Run(self, run_uuid)
        r.set_tag("mlflow.runName", run_name)
        if parent is not None:
            r.set_tag("mlflow.parentRunId", parent.run_uuid)
        if params:
            r.log_params(params)
        self._conn.commit()
        return r

    # -- queries (used by aggregation / analysis scripts) ------------------
    def child_runs(self, parent_uuid: str) -> list[str]:
        rows = self._conn.execute(
            "SELECT run_uuid FROM tags WHERE key='mlflow.parentRunId' AND value=?",
            (parent_uuid,),
        ).fetchall()
        return [r[0] for r in rows]

    def metric_series(self, run_uuid: str, key: str) -> list[tuple[int, float]]:
        rows = self._conn.execute(
            "SELECT step, value, is_nan FROM metrics WHERE run_uuid=? AND"
            " key=? ORDER BY step",
            (run_uuid, key),
        ).fetchall()
        return [(int(s), float("nan") if n else float(v)) for s, v, n in rows]

    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        return self._conn.execute(sql, params).fetchall()

    def close(self) -> None:
        self._conn.commit()
        self._conn.close()
