from coda_tpu.tracking.store import Run, TrackingStore

__all__ = ["TrackingStore", "Run"]
