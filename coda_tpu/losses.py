"""Loss registry.

Capability parity with the reference loss registry (reference
``coda/options.py:3-19``): ``'acc'`` is 1 - accuracy. The reference leaves
``'ce'`` as a TODO ("we don't have logits"); here cross-entropy on
post-softmax scores is implemented directly as ``-log p[label]`` with a
floor clamp, since the prediction tensor rows are probability vectors.

All loss fns are pure, elementwise-over-the-leading-axes, and jit-safe:
``loss_fn(preds (..., C), labels (...)) -> (...)`` float32.
"""

from __future__ import annotations

import jax.numpy as jnp


def accuracy_loss(preds: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """1 - accuracy, unreduced. ``labels`` may be int classes or one-hot."""
    pred_cls = jnp.argmax(preds, axis=-1)
    if labels.ndim == preds.ndim:  # one-hot / soft labels
        label_cls = jnp.argmax(labels, axis=-1)
    else:
        label_cls = labels
    return 1.0 - (pred_cls == label_cls).astype(jnp.float32)


def cross_entropy_loss(preds: jnp.ndarray, labels: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """-log p[label] on post-softmax scores, unreduced."""
    if labels.ndim == preds.ndim:
        p = jnp.sum(preds * labels, axis=-1)
    else:
        p = jnp.take_along_axis(preds, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -jnp.log(jnp.clip(p, eps, None))


LOSS_FNS = {
    "acc": accuracy_loss,
    "ce": cross_entropy_loss,
}
