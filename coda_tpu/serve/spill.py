"""Cold-tier spill store v2: append-log + in-memory index + compression.

The cold tier's first incarnation hibernated each session to its own
``hibernated_<sid>.json`` file. That is transparent and crash-obvious, but
it does not survive contact with the ROADMAP's literal million sessions:
1M inodes, 1M ``open()`` syscalls to re-index at startup, and the
uncompressed JSON payload (base64 carries + full row history) at ~10-40 KB
per session puts tens of GB on disk for state that compresses 5-10x.

This module replaces it with a single append-only log:

  * **records** — one frame per hibernate: a JSON header line
    ``{"sid", "n", "crc", ...}`` followed by exactly ``n`` bytes of
    zlib-compressed JSON payload and a trailing newline. Appends are
    O(payload) with one ``flush``; a process killed mid-append leaves a
    torn FINAL frame, which the scan drops (the same contract as the
    recorder's JSONL streams).
  * **index** — an in-memory ``sid -> (offset, length)`` map rebuilt by
    scanning the log at startup: last frame per sid wins, a tombstone
    frame (``"tomb": true``) deletes. A million sessions index in one
    sequential read of headers (the payloads are seeked over, not read).
  * **compaction on startup** — when dead bytes (superseded frames,
    tombstones) exceed half the log, the live set is rewritten to a fresh
    log and atomically swapped in. Runtime appends never pay compaction.
  * **legacy layout readable** — ``hibernated_<sid>.json`` files from the
    v1 store are indexed at startup and served transparently; startup
    compaction folds them into the log and removes the per-file copies,
    so a v1 spill dir upgrades itself on first start.

Thread safety: one lock around the index and the append fd. Reads seek on
a separate fd so a ``get`` never blocks behind an in-flight append's
flush.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Iterator, Optional

#: the v1 per-file layout (still readable; compaction folds it in)
LEGACY_PREFIX = "hibernated_"
#: the v2 append-log
LOG_NAME = "spill.log"
#: rewrite the log at startup when dead bytes exceed this fraction
COMPACT_GARBAGE_FRAC = 0.5


def _legacy_path(spill_dir: str, sid: str) -> str:
    return os.path.join(spill_dir, f"{LEGACY_PREFIX}{sid}.json")


class SpillStore:
    """Append-log session hibernation store (see module docstring).

    The public surface the tier manager needs: ``put``/``get``/``delete``/
    ``sids``/``__contains__``/``__len__``. Payloads are JSON-able dicts
    (the export payload); the store owns serialization + compression.
    """

    def __init__(self, spill_dir: str, compact: bool = True):
        self.dir = spill_dir
        self.log_path = os.path.join(spill_dir, LOG_NAME)
        self._lock = threading.Lock()
        # sid -> (offset, n_bytes) into the log, or the LEGACY marker
        # (None, path) for a v1 per-file payload not yet folded in
        self._index: dict[str, tuple] = {}
        # dead bytes (superseded/tombstone frames) as measured by the
        # startup scan — the compact-on-startup decision's input; runtime
        # appends don't maintain it (compaction never runs at runtime)
        self._dead_bytes = 0
        # tombstones whose append failed (ENOSPC): retried before the
        # next successful append so a deleted sid cannot silently
        # resurrect at the next startup scan
        self._tomb_retry: set[str] = set()
        self.compactions = 0      # startup compactions run
        self.put_errors = 0       # appends that failed (caller keeps warm)
        os.makedirs(spill_dir, exist_ok=True)
        self._scan()
        if compact and self._wants_compaction():
            self.compact()
        self._append_fd = open(self.log_path, "ab")

    # -- startup scan ------------------------------------------------------
    def _scan(self) -> None:
        """Rebuild the index: legacy files first (a log frame for the same
        sid supersedes its per-file copy), then one sequential pass over
        the log headers. A torn final frame is truncated away — the crash
        the append path's single-flush contract allows."""
        for fn in sorted(os.listdir(self.dir)):
            if fn.startswith(LEGACY_PREFIX) and fn.endswith(".json"):
                sid = fn[len(LEGACY_PREFIX):-len(".json")]
                self._index[sid] = (None, os.path.join(self.dir, fn))
        if not os.path.exists(self.log_path):
            return
        good_end = 0
        extents: dict[str, tuple] = {}   # sid -> (head_off, frame_end)
        with open(self.log_path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            while True:
                head_off = f.tell()
                line = f.readline()
                if not line:
                    break
                try:
                    head = json.loads(line)
                    n = int(head["n"])
                    sid = head["sid"]
                except (ValueError, KeyError, TypeError):
                    break  # torn/garbage frame: the log ends here
                payload_off = f.tell()
                if payload_off + n + 1 > size:
                    break  # torn payload (crash mid-append)
                f.seek(payload_off + n)
                if f.read(1) != b"\n":
                    break  # frame not terminated: torn
                good_end = f.tell()
                prev = extents.pop(sid, None)
                if prev is not None:
                    self._dead_bytes += prev[1] - prev[0]  # superseded
                if head.get("tomb"):
                    self._index.pop(sid, None)
                    self._dead_bytes += good_end - head_off
                else:
                    # a log frame supersedes a legacy file too (the legacy
                    # copy becomes garbage compaction removes)
                    self._index[sid] = (payload_off, n)
                    extents[sid] = (head_off, good_end)
        if good_end < size:
            # drop the torn tail so the next append starts on a frame
            # boundary instead of gluing onto half a record
            with open(self.log_path, "ab") as f:
                f.truncate(good_end)

    def _wants_compaction(self) -> bool:
        try:
            size = os.path.getsize(self.log_path)
        except OSError:
            size = 0
        has_legacy = any(off is None for off, _ in self._index.values())
        return has_legacy or (
            size > 0 and self._dead_bytes > COMPACT_GARBAGE_FRAC * size)

    # -- frame codec -------------------------------------------------------
    @staticmethod
    def _encode(payload: dict) -> bytes:
        return zlib.compress(
            json.dumps(payload, separators=(",", ":")).encode(), 6)

    def _frame(self, sid: str, zbytes: Optional[bytes]) -> bytes:
        head: dict = {"sid": sid, "n": len(zbytes or b"")}
        if zbytes is None:
            head = {"sid": sid, "n": 0, "tomb": True}
            zbytes = b""
        else:
            head["crc"] = zlib.crc32(zbytes)
        return json.dumps(head, separators=(",", ":")).encode() \
            + b"\n" + zbytes + b"\n"

    def _read_at(self, offset: int, n: int) -> dict:
        with open(self.log_path, "rb") as f:
            f.seek(offset)
            zbytes = f.read(n)
        return json.loads(zlib.decompress(zbytes))

    def _append_locked(self, frame: bytes) -> Optional[int]:
        """Append one frame under the lock; returns its start offset, or
        None on failure — with the tail rewound, because a partial write
        (ENOSPC mid-flush) would otherwise make the startup scan's
        torn-tail truncation drop every valid frame appended after it."""
        offset = self._append_fd.tell()
        try:
            self._append_fd.write(frame)
            self._append_fd.flush()
            return offset
        except OSError:
            try:
                self._append_fd.seek(offset)
                self._append_fd.truncate(offset)
            except OSError:
                pass  # scan-time truncation remains the backstop
            self.put_errors += 1
            return None

    def _flush_tombstones_locked(self) -> None:
        for sid in list(self._tomb_retry):
            if self._append_locked(self._frame(sid, None)) is None:
                return  # disk still unhappy; keep retrying later
            self._tomb_retry.discard(sid)

    # -- the store surface -------------------------------------------------
    def put(self, sid: str, payload: dict) -> bool:
        """Append one hibernate frame; False (counted) when the disk write
        failed — the caller keeps the session warm, never lost."""
        zbytes = self._encode(payload)
        frame = self._frame(sid, zbytes)
        with self._lock:
            self._flush_tombstones_locked()  # deletes land before puts
            offset = self._append_locked(frame)
            if offset is None:
                return False
            payload_off = offset + frame.index(b"\n") + 1
            self._index[sid] = (payload_off, len(zbytes))
        # a log frame supersedes the legacy per-file copy
        try:
            os.remove(_legacy_path(self.dir, sid))
        except OSError:
            pass
        return True

    def get(self, sid: str) -> Optional[dict]:
        with self._lock:
            entry = self._index.get(sid)
        if entry is None:
            return None
        offset, ref = entry
        try:
            if offset is None:          # legacy per-file payload
                with open(ref) as f:
                    return json.load(f)
            return self._read_at(offset, ref)
        except (OSError, ValueError, zlib.error):
            return None

    def delete(self, sid: str) -> bool:
        """Tombstone one sid (and drop its legacy file, if any). A failed
        tombstone append is queued and retried before the next append —
        without that, a restart's scan would re-index the last live
        frame and resurrect a session the server confirmed closed."""
        with self._lock:
            entry = self._index.pop(sid, None)
            if entry is None:
                return False
            offset, ref = entry
            if offset is not None:
                if self._append_locked(self._frame(sid, None)) is None:
                    self._tomb_retry.add(sid)
        if offset is None:
            try:
                os.remove(ref)
            except OSError:
                pass
        return True

    def sids(self) -> list[str]:
        with self._lock:
            return list(self._index)

    def __contains__(self, sid: str) -> bool:
        with self._lock:
            return sid in self._index

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def items(self) -> Iterator[tuple]:
        """(sid, payload) over the live set (the export-parked sweep)."""
        for sid in self.sids():
            payload = self.get(sid)
            if payload is not None:
                yield sid, payload

    # -- compaction --------------------------------------------------------
    def compact(self) -> dict:
        """Rewrite the log with only live frames (legacy files folded in
        and removed), atomically swapped. Startup-only by construction —
        the caller runs it before the append fd opens."""
        tmp = self.log_path + ".tmp"
        new_index: dict[str, tuple] = {}
        legacy_done: list[str] = []
        n_live = 0
        with open(tmp, "wb") as out:
            for sid in list(self._index):
                entry = self._index.get(sid)
                if entry is None:
                    continue
                offset, ref = entry
                try:
                    if offset is None:
                        with open(ref) as f:
                            zbytes = self._encode(json.load(f))
                        legacy_done.append(ref)
                    else:
                        with open(self.log_path, "rb") as f:
                            f.seek(offset)
                            zbytes = f.read(ref)
                        json.loads(zlib.decompress(zbytes))  # verify
                except (OSError, ValueError, zlib.error):
                    continue  # unreadable frame: dropped, not copied
                frame = self._frame(sid, zbytes)
                head_off = out.tell()
                out.write(frame)
                new_index[sid] = (head_off + frame.index(b"\n") + 1,
                                  len(zbytes))
                n_live += 1
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, self.log_path)
        self._index = new_index
        self._dead_bytes = 0
        self.compactions += 1
        for path in legacy_done:
            try:
                os.remove(path)
            except OSError:
                pass
        return {"live": n_live, "legacy_folded": len(legacy_done)}

    def close(self) -> None:
        with self._lock:
            self._flush_tombstones_locked()  # last chance to persist
            try:
                self._append_fd.close()
            except OSError:
                pass
