"""Cold-tier spill store v3: sharded segments + sidecar index + lazy frames.

The v2 store (one ``spill.log`` append-log) fixed the v1 per-file layout's
inode storm, but it still had three costs that dominate at the ROADMAP's
literal million sessions:

  * **startup was O(frames)** — every start re-scanned every header in
    the log sequentially, even though almost all of them were already
    known at the last clean shutdown;
  * **wake decompressed the whole payload** — one zlib stream held the
    metadata, the row history AND every slab carry, so a wake (or even a
    failed digest check) paid full decompression of arrays it might
    never use;
  * **compaction stopped the world** — the whole log was rewritten in
    one pass before the append fd opened, so a garbage-heavy store
    serialized its entire live set on the startup path.

v3 replaces the single log with a sharded layout per spill dir (one spill
dir per replica — the fleet already gives each replica its own subdir):

  * **segments** — ``seg_<n>.log`` files, appended in order, sealed and
    rolled at :data:`SEGMENT_MAX_BYTES`. A frame is a JSON header line
    ``{"sid", "parts": [[name, nbytes, crc32], ...]}`` followed by the
    concatenated zlib-compressed part streams and a trailing newline
    (tombstones: ``{"sid", "tomb": true}``). The payload is split into a
    ``meta`` part (the export payload minus arrays: task, spec, rows)
    and one part per slab carry leaf, so decompression is per-leaf.
  * **sidecar index** — ``spill_index.json``, atomically replaced after
    compactions, on close, and every :data:`INDEX_FLUSH_EVERY`
    mutations. Startup loads the index and scans ONLY the bytes
    appended after it was written (the per-segment recorded size is the
    scan cursor), truncating a torn tail — O(index + tail), not
    O(frames). A missing/corrupt index degrades to a full scan, never
    to data loss; ``startup_mode`` / ``startup_scan_frames`` report
    which path ran (the 1M-session artifact's evidence).
  * **lazy reads** — ``get`` returns a :class:`LazyPayload`: the
    segment is mmap'd, the ``meta`` part is decoded eagerly (it is what
    every import touches first), and each carry leaf decompresses only
    when accessed — a wake is zero-copy on the array bytes until the
    import path's digest check actually reads them.
    :func:`materialize` converts back to a JSON-safe dict for the
    export/migration surfaces.
  * **per-segment compaction** — a sealed segment whose garbage
    fraction exceeds :data:`COMPACT_GARBAGE_FRAC` has its live frames
    copied forward into the active segment as raw bytes (no
    decompression) one short lock window per frame, then the segment is
    unlinked. Concurrent gets keep working: an open mmap pins the
    unlinked file's data. Nothing ever rewrites the whole store.
  * **legacy layouts fold in** — a v2 ``spill.log`` and v1
    ``hibernated_<sid>.json`` files are read at startup, re-encoded
    into v3 segments, and removed (counted in ``compactions``), so an
    old spill dir upgrades itself on first start.

Thread safety: one lock around the index tables and the active-segment
append fd. Compression happens OUTSIDE the lock (``encode`` /
``put_encoded`` — the tier manager uses the split API so a big demotion
batch no longer stalls concurrent wakes behind zlib); reads mmap the
segment without the lock.
"""

from __future__ import annotations

import base64
import json
import mmap
import os
import threading
import zlib
from collections.abc import Mapping
from typing import Iterator, Optional

#: the v1 per-file layout (still readable; startup folds it in)
LEGACY_PREFIX = "hibernated_"
#: the v2 single append-log (still readable; startup folds it in)
LOG_NAME = "spill.log"
#: v3 segment files: ``seg_<8-digit counter>.log``
SEGMENT_PREFIX = "seg_"
#: the persisted sidecar index
INDEX_NAME = "spill_index.json"
INDEX_VERSION = 3
#: seal + roll the active segment past this many bytes
SEGMENT_MAX_BYTES = 4 << 20
#: compact a sealed segment when dead bytes exceed this fraction
COMPACT_GARBAGE_FRAC = 0.5
#: rewrite the sidecar index after this many puts/deletes
INDEX_FLUSH_EVERY = 256


def _legacy_path(spill_dir: str, sid: str) -> str:
    return os.path.join(spill_dir, f"{LEGACY_PREFIX}{sid}.json")


def _seg_name(n: int) -> str:
    return f"{SEGMENT_PREFIX}{n:08d}.log"


def _seg_num(name: str) -> Optional[int]:
    if not (name.startswith(SEGMENT_PREFIX) and name.endswith(".log")):
        return None
    try:
        return int(name[len(SEGMENT_PREFIX):-len(".log")])
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# payload <-> parts codec
# ---------------------------------------------------------------------------
# An export payload's arrays (slab carries + PRNG key, packed by
# recovery._pack as {"dtype","shape","data"}) become their own
# compressed parts holding RAW array bytes (not base64 — a third
# smaller before compression even starts); everything else (task, spec,
# rows, parked answers) is the "meta" part. A payload without carries
# (stream-only export, or a non-export dict) is a single meta part.

_ARRAY_KEYS = ("carries", "key")


def _is_packed(d) -> bool:
    return (isinstance(d, Mapping) and "dtype" in d and "shape" in d
            and "data" in d)


def _raw_bytes(data) -> bytes:
    if isinstance(data, str):
        return base64.b64decode(data)
    return bytes(data)


def encode_payload(payload: Mapping) -> list:
    """Split + compress a payload into ``[(name, zbytes), ...]`` with no
    lock held — the caller appends the result via :meth:`SpillStore.
    put_encoded`. Pure function of the payload."""
    meta = dict(payload)
    parts = []
    carries = meta.get("carries")
    if isinstance(carries, (list, tuple)) and all(
            _is_packed(c) for c in carries):
        spec = []
        for i, c in enumerate(carries):
            name = f"c{i}"
            spec.append({"dtype": c["dtype"], "shape": list(c["shape"]),
                         "part": name})
            parts.append((name, zlib.compress(_raw_bytes(c["data"]), 6)))
        meta["carries"] = {"__parts__": spec}
    key = meta.get("key")
    if _is_packed(key):
        meta["key"] = {"__parts__": [{"dtype": key["dtype"],
                                      "shape": list(key["shape"]),
                                      "part": "key"}]}
        parts.append(("key", zlib.compress(_raw_bytes(key["data"]), 6)))
    zmeta = zlib.compress(
        json.dumps(meta, separators=(",", ":")).encode(), 6)
    return [("meta", zmeta)] + parts


def _frame(sid: str, parts: Optional[list]) -> bytes:
    if parts is None:
        head = {"sid": sid, "tomb": True}
        body = b""
    else:
        head = {"sid": sid,
                "parts": [[name, len(z), zlib.crc32(z)]
                          for name, z in parts]}
        body = b"".join(z for _, z in parts)
    return json.dumps(head, separators=(",", ":")).encode() \
        + b"\n" + body + b"\n"


class _LazyLeaf(Mapping):
    """One packed array whose ``data`` decompresses on first access."""

    def __init__(self, spec: dict, mm, off: int, n: int):
        self._spec, self._mm, self._off, self._n = spec, mm, off, n
        self._data: Optional[bytes] = None

    def __getitem__(self, k):
        if k == "data":
            if self._data is None:
                self._data = zlib.decompress(self._mm[self._off:
                                                      self._off + self._n])
            return self._data
        if k in ("dtype", "shape"):
            return self._spec[k]
        raise KeyError(k)

    def __iter__(self):
        return iter(("dtype", "shape", "data"))

    def __len__(self):
        return 3


class LazyPayload(Mapping):
    """An mmap-backed export payload: meta decoded eagerly, carry leaves
    decompressed per-leaf on access. Compares equal to (and
    :func:`materialize`-s into) the plain dict it was encoded from."""

    def __init__(self, mm, meta: dict, part_offs: dict):
        self._mm = mm
        self._meta = meta
        self._offs = part_offs       # name -> (abs_off, nbytes)
        self._cache: dict = {}

    def _resolve(self, k):
        v = self._meta[k]
        if isinstance(v, dict) and "__parts__" in v:
            leaves = []
            for spec in v["__parts__"]:
                off, n = self._offs[spec["part"]]
                leaves.append(_LazyLeaf(spec, self._mm, off, n))
            return leaves[0] if k == "key" else leaves
        return v

    def __getitem__(self, k):
        if k not in self._cache:
            self._cache[k] = self._resolve(k)
        return self._cache[k]

    def __iter__(self):
        return iter(self._meta)

    def __len__(self):
        return len(self._meta)

    def __eq__(self, other):
        if isinstance(other, LazyPayload):
            other = materialize(other)
        if isinstance(other, Mapping):
            return materialize(self) == dict(other)
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq


def materialize(payload) -> Optional[dict]:
    """A JSON-safe plain dict of ``payload`` (array bytes back to
    base64). The export/migration surfaces call this at the serialization
    boundary; a plain dict passes through untouched."""
    if payload is None or not isinstance(payload, Mapping):
        return payload
    if _is_packed(payload):
        data = payload["data"]
        if not isinstance(data, str):
            data = base64.b64encode(bytes(data)).decode("ascii")
        return {"dtype": payload["dtype"],
                "shape": list(payload["shape"]), "data": data}
    out = {}
    for k in payload:
        v = payload[k]
        if _is_packed(v):
            v = materialize(v)
        elif isinstance(v, (list, tuple)) and v and all(
                _is_packed(c) for c in v):
            v = [materialize(c) for c in v]
        out[k] = v
    return out


class SpillStore:
    """Sharded-segment session hibernation store (see module docstring).

    Public surface (the tier manager's contract): ``put``/``get``/
    ``delete``/``sids``/``__contains__``/``__len__``/``items``, plus the
    split ``encode``/``put_encoded`` pair so compression can run outside
    any caller-side lock, ``maybe_compact`` for the sweeper, and
    ``stats`` for the gauges.
    """

    def __init__(self, spill_dir: str, compact: bool = True):
        self.dir = spill_dir
        self._lock = threading.Lock()
        # sid -> (seg_name, head_off, frame_len)
        self._index: dict[str, tuple] = {}
        # seg_name -> {"size": scanned/appended bytes, "garbage": bytes}
        self._segs: dict[str, dict] = {}
        # tombstones whose append failed (ENOSPC): retried before the
        # next successful append so a deleted sid cannot silently
        # resurrect at the next startup scan
        self._tomb_retry: set[str] = set()
        self.compactions = 0          # legacy folds + segment compactions
        self.segment_compactions = 0  # v3 per-segment compactions only
        self.put_errors = 0           # appends that failed (caller keeps warm)
        self.startup_mode = "scan"    # "index" (sidecar honored) | "scan"
        self.startup_scan_frames = 0  # frames the startup actually parsed
        self._mutations = 0           # puts/deletes since last index write
        os.makedirs(spill_dir, exist_ok=True)
        self._startup()
        self._open_active()
        self._fold_legacy()
        if compact:
            self.maybe_compact()
        self._write_index()

    # -- paths -------------------------------------------------------------
    def _seg_path(self, seg: str) -> str:
        return os.path.join(self.dir, seg)

    @property
    def index_path(self) -> str:
        return os.path.join(self.dir, INDEX_NAME)

    # -- startup -----------------------------------------------------------
    def _startup(self) -> None:
        names = sorted(
            (n for n in os.listdir(self.dir) if _seg_num(n) is not None),
            key=_seg_num)
        cursors = {n: 0 for n in names}   # per-segment scan start
        loaded = self._load_index()
        if loaded is not None:
            entries, sizes = loaded
            ok = True
            for seg, rec in sizes.items():
                if seg not in cursors:
                    ok = False      # a recorded segment vanished: rescan
                    break
                actual = os.path.getsize(self._seg_path(seg))
                if actual < rec["size"]:
                    ok = False      # truncated under us: rescan
                    break
            if ok:
                self.startup_mode = "index"
                for sid, (seg, off, ln) in entries.items():
                    self._index[sid] = (seg, off, ln)
                for seg, rec in sizes.items():
                    self._segs[seg] = {"size": rec["size"],
                                       "garbage": rec["garbage"]}
                    cursors[seg] = rec["size"]
        # scan only what the index does not cover: whole segments under
        # "scan", appended tails (or brand-new segments) under "index"
        for seg in names:
            self._segs.setdefault(seg, {"size": 0, "garbage": 0})
            self._scan_segment(seg, cursors[seg])

    def _load_index(self):
        try:
            with open(self.index_path) as f:
                idx = json.load(f)
            if idx.get("v") != INDEX_VERSION:
                return None
            entries = {sid: tuple(e) for sid, e in idx["entries"].items()}
            sizes = {seg: {"size": int(rec["size"]),
                           "garbage": int(rec["garbage"])}
                     for seg, rec in idx["segments"].items()}
            return entries, sizes
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _scan_segment(self, seg: str, start: int) -> None:
        """Index frames from ``start`` to EOF; a torn final frame is
        truncated away — the crash the append path's single-flush
        contract allows."""
        path = self._seg_path(seg)
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if start >= size:
            return
        good_end = start
        with open(path, "rb") as f:
            f.seek(start)
            while True:
                head_off = f.tell()
                line = f.readline()
                if not line:
                    break
                try:
                    head = json.loads(line)
                    sid = head["sid"]
                    body = (0 if head.get("tomb") else
                            sum(int(p[1]) for p in head["parts"]))
                except (ValueError, KeyError, TypeError, IndexError):
                    break  # torn/garbage header: the segment ends here
                body_off = f.tell()
                if body_off + body + 1 > size:
                    break  # torn body (crash mid-append)
                f.seek(body_off + body)
                if f.read(1) != b"\n":
                    break  # frame not terminated: torn
                good_end = f.tell()
                frame_len = good_end - head_off
                self.startup_scan_frames += 1
                self._supersede_locked(sid)
                if head.get("tomb"):
                    self._segs[seg]["garbage"] += frame_len
                else:
                    self._index[sid] = (seg, head_off, frame_len)
                self._segs[seg]["size"] = good_end
        if good_end < size:
            with open(path, "ab") as f:
                f.truncate(good_end)

    def _supersede_locked(self, sid: str) -> None:
        prev = self._index.pop(sid, None)
        if prev is not None:
            pseg, _, plen = prev
            if pseg in self._segs:
                self._segs[pseg]["garbage"] += plen

    def _fold_legacy(self) -> None:
        """Re-encode v1 per-file and v2 single-log payloads into v3
        segments, then remove the old layout (upgrade-on-first-start)."""
        folded = 0
        v2 = os.path.join(self.dir, LOG_NAME)
        if os.path.exists(v2):
            for sid, payload in self._scan_v2(v2):
                if self._append_parts(sid, encode_payload(payload),
                                      startup=True):
                    folded += 1
            try:
                os.remove(v2)
            except OSError:
                pass
        for fn in sorted(os.listdir(self.dir)):
            if not (fn.startswith(LEGACY_PREFIX) and fn.endswith(".json")):
                continue
            sid = fn[len(LEGACY_PREFIX):-len(".json")]
            path = os.path.join(self.dir, fn)
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue  # unreadable legacy file: left in place
            if sid not in self._index:   # a v2/v3 frame supersedes v1
                if not self._append_parts(sid, encode_payload(payload),
                                          startup=True):
                    continue
            folded += 1
            try:
                os.remove(path)
            except OSError:
                pass
        if folded:
            self.compactions += 1

    @staticmethod
    def _scan_v2(path: str) -> Iterator[tuple]:
        """(sid, payload) for the live set of a v2 append-log: last frame
        per sid wins, tombstones delete, torn tail dropped."""
        frames: dict[str, Optional[dict]] = {}
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                while True:
                    line = f.readline()
                    if not line:
                        break
                    try:
                        head = json.loads(line)
                        n = int(head["n"])
                        sid = head["sid"]
                    except (ValueError, KeyError, TypeError):
                        break
                    off = f.tell()
                    if off + n + 1 > size:
                        break
                    zbytes = f.read(n)
                    if f.read(1) != b"\n":
                        break
                    if head.get("tomb"):
                        frames[sid] = None
                    else:
                        try:
                            frames[sid] = json.loads(zlib.decompress(zbytes))
                        except (ValueError, zlib.error):
                            frames.pop(sid, None)
        except OSError:
            return
        for sid, payload in frames.items():
            if payload is not None:
                yield sid, payload

    # -- the active segment ------------------------------------------------
    def _open_active(self) -> None:
        nums = [_seg_num(s) for s in self._segs]
        cur = max([n for n in nums if n is not None], default=0)
        if cur == 0:
            cur = 1
            self._segs[_seg_name(1)] = {"size": 0, "garbage": 0}
        self._active = _seg_name(cur)
        self._append_fd = open(self._seg_path(self._active), "ab")
        if self._segs[self._active]["size"] >= SEGMENT_MAX_BYTES:
            self._roll_locked()

    def _roll_locked(self) -> None:
        try:
            self._append_fd.close()
        except OSError:
            pass
        nxt = _seg_name(_seg_num(self._active) + 1)
        self._segs[nxt] = {"size": 0, "garbage": 0}
        self._active = nxt
        self._append_fd = open(self._seg_path(nxt), "ab")

    def _append_locked(self, frame: bytes):
        """Append one frame to the active segment under the lock; returns
        ``(seg, offset)`` or None on failure — with the tail rewound,
        because a partial write (ENOSPC mid-flush) would otherwise make
        the startup scan's torn-tail truncation drop every valid frame
        appended after it."""
        try:
            offset = self._append_fd.tell()
            self._append_fd.write(frame)
            self._append_fd.flush()
        except (OSError, ValueError):   # ValueError: fd already closed
            try:
                self._append_fd.seek(offset)
                self._append_fd.truncate(offset)
            except (OSError, ValueError, UnboundLocalError):
                pass  # scan-time truncation remains the backstop
            self.put_errors += 1
            return None
        seg = self._active
        self._segs[seg]["size"] = offset + len(frame)
        if self._segs[seg]["size"] >= SEGMENT_MAX_BYTES:
            self._roll_locked()
        return seg, offset

    def _append_parts(self, sid: str, parts: list,
                      startup: bool = False) -> bool:
        frame = _frame(sid, parts)
        with self._lock:
            if not startup:
                self._flush_tombstones_locked()  # deletes land before puts
            at = self._append_locked(frame)
            if at is None:
                return False
            self._supersede_locked(sid)
            self._index[sid] = (at[0], at[1], len(frame))
            self._mutations += 1
        return True

    def _flush_tombstones_locked(self) -> None:
        for sid in list(self._tomb_retry):
            if self._append_locked(_frame(sid, None)) is None:
                return  # disk still unhappy; keep retrying later
            self._tomb_retry.discard(sid)

    # -- the store surface -------------------------------------------------
    def encode(self, payload: Mapping) -> list:
        """Compress a payload into appendable parts — NO lock held, so
        the tier manager can run zlib outside its own lock too."""
        return encode_payload(payload)

    def put_encoded(self, sid: str, parts: list) -> bool:
        """Append a pre-encoded payload (one short lock window); False
        (counted) when the disk write failed — the caller keeps the
        session warm, never lost."""
        ok = self._append_parts(sid, parts)
        if ok:
            try:
                os.remove(_legacy_path(self.dir, sid))
            except OSError:
                pass
            self._maybe_flush_index()
        return ok

    def put(self, sid: str, payload: Mapping) -> bool:
        """``encode`` (outside the lock) + ``put_encoded``."""
        return self.put_encoded(sid, self.encode(payload))

    def get(self, sid: str):
        """The payload as a :class:`LazyPayload` (meta decoded, carry
        leaves decompressed on access), or None."""
        with self._lock:
            entry = self._index.get(sid)
        if entry is None:
            return None
        seg, head_off, frame_len = entry
        try:
            with open(self._seg_path(seg), "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            return None
        try:
            nl = mm.find(b"\n", head_off, head_off + frame_len)
            head = json.loads(mm[head_off:nl])
            offs, cur = {}, nl + 1
            for name, n, _crc in head.get("parts", []):
                offs[name] = (cur, int(n))
                cur += int(n)
            moff, mn = offs["meta"]
            meta = json.loads(zlib.decompress(mm[moff:moff + mn]))
            return LazyPayload(mm, meta, offs)
        except (ValueError, KeyError, zlib.error, IndexError):
            return None

    def delete(self, sid: str) -> bool:
        """Tombstone one sid (and drop its legacy file, if any). A failed
        tombstone append is queued and retried before the next append —
        without that, a restart's scan would re-index the last live
        frame and resurrect a session the server confirmed closed."""
        with self._lock:
            entry = self._index.pop(sid, None)
            if entry is None:
                return False
            seg, _, frame_len = entry
            if seg in self._segs:
                self._segs[seg]["garbage"] += frame_len
            if self._append_locked(_frame(sid, None)) is None:
                self._tomb_retry.add(sid)
            self._mutations += 1
        try:
            os.remove(_legacy_path(self.dir, sid))
        except OSError:
            pass
        self._maybe_flush_index()
        return True

    def sids(self) -> list:
        with self._lock:
            return list(self._index)

    def __contains__(self, sid: str) -> bool:
        with self._lock:
            return sid in self._index

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def items(self) -> Iterator[tuple]:
        """(sid, payload-dict) over the live set (the export sweep —
        materialized, the consumer serializes them)."""
        for sid in self.sids():
            payload = self.get(sid)
            if payload is not None:
                yield sid, materialize(payload)

    # -- compaction --------------------------------------------------------
    def _compactable_locked(self) -> list:
        out = []
        for seg, rec in self._segs.items():
            if seg == self._active or rec["size"] == 0:
                continue
            if rec["garbage"] > COMPACT_GARBAGE_FRAC * rec["size"]:
                out.append(seg)
        return out

    def maybe_compact(self) -> int:
        """Compact every sealed segment past the garbage threshold;
        returns how many were compacted. Safe at runtime: one short lock
        window per copied frame, concurrent gets read via mmaps that
        survive the unlink."""
        with self._lock:
            victims = self._compactable_locked()
        for seg in victims:
            self._compact_segment(seg)
        if victims:
            self._write_index()
        return len(victims)

    def _compact_segment(self, seg: str) -> None:
        """Copy the segment's live frames forward into the active segment
        as raw bytes (no decompression), then unlink it. Tombstones for
        sids that are gone from the index are copied forward too unless
        this is the oldest segment (nothing older could resurrect them);
        scan order stays correct because copies land in a NEWER segment
        than any frame they supersede."""
        path = self._seg_path(seg)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return
        with self._lock:
            oldest = seg == min(self._segs, key=_seg_num)
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                break
            try:
                head = json.loads(data[pos:nl])
                sid = head["sid"]
                body = (0 if head.get("tomb") else
                        sum(int(p[1]) for p in head.get("parts", [])))
            except (ValueError, KeyError, TypeError):
                break
            end = nl + 1 + body + 1
            if end > len(data) or data[end - 1:end] != b"\n":
                break
            frame = data[pos:end]
            with self._lock:
                entry = self._index.get(sid)
                live = entry is not None and entry[0] == seg \
                    and entry[1] == pos
                keep_tomb = (head.get("tomb") and sid not in self._index
                             and sid not in self._tomb_retry and not oldest)
                if live or keep_tomb:
                    at = self._append_locked(frame)
                    if at is None:
                        return  # disk full: abort, retry next sweep
                    if live:
                        self._index[sid] = (at[0], at[1], len(frame))
            pos = end
        with self._lock:
            self._segs.pop(seg, None)
        try:
            os.remove(path)
        except OSError:
            pass
        self.segment_compactions += 1
        self.compactions += 1

    # -- sidecar index -----------------------------------------------------
    def _maybe_flush_index(self) -> None:
        with self._lock:
            due = self._mutations >= INDEX_FLUSH_EVERY
            if due:
                self._mutations = 0
        if due:
            self._write_index()

    def _write_index(self) -> None:
        with self._lock:
            doc = {"v": INDEX_VERSION,
                   "entries": {sid: list(e)
                               for sid, e in self._index.items()},
                   "segments": {seg: {"size": rec["size"],
                                      "garbage": rec["garbage"]}
                                for seg, rec in self._segs.items()}}
            self._mutations = 0
        tmp = self.index_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.index_path)
        except OSError:
            pass  # next startup degrades to a scan, never to data loss

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            live = sum(ln for _, _, ln in self._index.values())
            size = sum(rec["size"] for rec in self._segs.values())
            garbage = sum(rec["garbage"] for rec in self._segs.values())
            return {
                "entries": len(self._index),
                "segments": len(self._segs),
                "live_bytes": live,
                "log_bytes": size,
                "garbage_bytes": garbage,
                "segment_compactions": self.segment_compactions,
                "compactions": self.compactions,
                "put_errors": self.put_errors,
                "startup_mode": self.startup_mode,
                "startup_scan_frames": self.startup_scan_frames,
            }

    def close(self) -> None:
        with self._lock:
            self._flush_tombstones_locked()  # last chance to persist
            try:
                self._append_fd.close()
            except OSError:
                pass
        self._write_index()
