"""Batched multi-session serving of interactive active model selection.

Multiplexes many concurrent human-in-the-loop selection sessions onto one
accelerator: a fixed-capacity slab of vmapped selector carries with
AOT-warmed, buffer-donated executables (:mod:`~coda_tpu.serve.state`), a
continuous-batching dispatcher that executes one compiled masked step per
tick (:mod:`~coda_tpu.serve.batcher`), a dependency-free asyncio HTTP/JSON
front door with admission control and a warm-pool readiness gate
(:mod:`~coda_tpu.serve.server`), per-dispatch metrics including the
queue-wait/dispatch/step attribution triplet
(:mod:`~coda_tpu.serve.metrics`), fault tolerance — session
checkpoint/restore + migration, bucket self-healing from recorder
streams, crash restore (:mod:`~coda_tpu.serve.recovery`) — a
deterministic fault-injection harness that exercises every recovery path
(:mod:`~coda_tpu.serve.faults`), and tiered posterior state: hot
sessions on the slab, warm sessions as host-RAM export payloads, cold
sessions hibernated to disk, with idle/watermark demotion and
transparent wake-on-label, so open sessions are bounded by RAM+disk
instead of slab capacity (:mod:`~coda_tpu.serve.tiering`). See
ARCHITECTURE.md §"Serving".
"""

from coda_tpu.serve.batcher import Batcher, Ticket
from coda_tpu.serve.faults import FaultInjected, FaultInjector
from coda_tpu.serve.fleet import Fleet, build_fleet
from coda_tpu.serve.router import (
    DeadReplica,
    HttpReplica,
    InprocReplica,
    SessionRouter,
    rendezvous_owner,
    rendezvous_rank,
)
from coda_tpu.serve.journal import MigrationJournal, payload_digest
from coda_tpu.serve.transport import (
    CircuitBreaker,
    ReplicaTransport,
    ReplicaUnavailable,
    RetryBudget,
    VERB_DEADLINES,
)
from coda_tpu.serve.metrics import ServeMetrics
from coda_tpu.serve.recovery import (
    BucketHealer,
    ImportRejected,
    ReplayMismatch,
    export_session,
    heal_bucket,
    import_session,
    restore_app_sessions,
)
from coda_tpu.serve.server import (
    AsyncHTTPServer,
    ServeApp,
    build_app,
    make_server,
)
from coda_tpu.serve.spill import SpillStore
from coda_tpu.serve.tiering import TierManager
from coda_tpu.serve.state import (
    Bucket,
    BucketQuarantined,
    SelectorSpec,
    Session,
    SessionStore,
    SlabFull,
    SlotRequest,
    SlotResult,
    StaleOwner,
    UnknownSession,
    make_slab_step,
)

__all__ = [
    "AsyncHTTPServer",
    "Batcher",
    "Bucket",
    "BucketHealer",
    "BucketQuarantined",
    "FaultInjected",
    "FaultInjector",
    "CircuitBreaker",
    "DeadReplica",
    "Fleet",
    "HttpReplica",
    "MigrationJournal",
    "ImportRejected",
    "InprocReplica",
    "ReplayMismatch",
    "ReplicaTransport",
    "ReplicaUnavailable",
    "RetryBudget",
    "SelectorSpec",
    "ServeApp",
    "ServeMetrics",
    "Session",
    "SessionRouter",
    "SessionStore",
    "SlabFull",
    "SpillStore",
    "StaleOwner",
    "VERB_DEADLINES",
    "SlotRequest",
    "SlotResult",
    "Ticket",
    "TierManager",
    "UnknownSession",
    "build_app",
    "build_fleet",
    "export_session",
    "heal_bucket",
    "import_session",
    "make_server",
    "make_slab_step",
    "rendezvous_owner",
    "rendezvous_rank",
    "restore_app_sessions",
]
