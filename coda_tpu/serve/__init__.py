"""Batched multi-session serving of interactive active model selection.

Multiplexes many concurrent human-in-the-loop selection sessions onto one
accelerator: a fixed-capacity slab of vmapped selector carries with
AOT-warmed, buffer-donated executables (:mod:`~coda_tpu.serve.state`), a
continuous-batching dispatcher that executes one compiled masked step per
tick (:mod:`~coda_tpu.serve.batcher`), a dependency-free asyncio HTTP/JSON
front door with admission control and a warm-pool readiness gate
(:mod:`~coda_tpu.serve.server`), and per-dispatch metrics including the
queue-wait/dispatch/step attribution triplet
(:mod:`~coda_tpu.serve.metrics`). See ARCHITECTURE.md §"Serving".
"""

from coda_tpu.serve.batcher import Batcher, Ticket
from coda_tpu.serve.metrics import ServeMetrics
from coda_tpu.serve.server import (
    AsyncHTTPServer,
    ServeApp,
    build_app,
    make_server,
)
from coda_tpu.serve.state import (
    Bucket,
    SelectorSpec,
    Session,
    SessionStore,
    SlabFull,
    SlotRequest,
    SlotResult,
    UnknownSession,
    make_slab_step,
)

__all__ = [
    "AsyncHTTPServer",
    "Batcher",
    "Bucket",
    "SelectorSpec",
    "ServeApp",
    "ServeMetrics",
    "Session",
    "SessionStore",
    "SlabFull",
    "SlotRequest",
    "SlotResult",
    "Ticket",
    "UnknownSession",
    "build_app",
    "make_server",
    "make_slab_step",
]
