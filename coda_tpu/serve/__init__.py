"""Batched multi-session serving of interactive active model selection.

Multiplexes many concurrent human-in-the-loop selection sessions onto one
accelerator: a fixed-capacity slab of vmapped selector carries
(:mod:`~coda_tpu.serve.state`), a micro-batching dispatcher that executes
one compiled masked step per tick (:mod:`~coda_tpu.serve.batcher`), a
dependency-free HTTP/JSON front door with admission control
(:mod:`~coda_tpu.serve.server`), and per-dispatch metrics
(:mod:`~coda_tpu.serve.metrics`). See ARCHITECTURE.md §"Serving".
"""

from coda_tpu.serve.batcher import Batcher, Ticket
from coda_tpu.serve.metrics import ServeMetrics
from coda_tpu.serve.server import ServeApp, build_app, make_server
from coda_tpu.serve.state import (
    Bucket,
    SelectorSpec,
    Session,
    SessionStore,
    SlabFull,
    SlotRequest,
    SlotResult,
    UnknownSession,
    make_slab_step,
)

__all__ = [
    "Batcher",
    "Bucket",
    "SelectorSpec",
    "ServeApp",
    "ServeMetrics",
    "Session",
    "SessionStore",
    "SlabFull",
    "SlotRequest",
    "SlotResult",
    "Ticket",
    "UnknownSession",
    "build_app",
    "make_server",
    "make_slab_step",
]
