"""Session slabs: many interactive selection sessions as one vmapped carry.

``demo/app.py`` drives exactly one ``InteractiveSelector`` per user, paying a
host↔device round trip per click. But the paper's loop (score → pick →
oracle label → posterior update → best) is embarrassingly batchable across
independent sessions — the same insight that makes seeds a ``vmap`` axis in
``engine/loop.py``. This module holds the device-side half of the serving
layer:

  * a **bucket** is a fixed-capacity slab of selector carries for one
    (task, selector-config) pair: the state pytree with a leading SLOT axis,
    a per-slot PRNG key array, and a host-side free list. One jit-compiled
    **masked step** (update-if-requested + select + best, ``vmap`` over
    slots) serves every session in the bucket per dispatch;
  * the **SessionStore** multiplexes sessions onto buckets: admission takes
    a free slot (or refuses — the backpressure signal the server turns into
    HTTP 503), close returns the slot for reuse.

Key-stream parity: a session's randomness is bit-identical to driving one
``InteractiveSelector(selector, seed)`` by hand — init consumes one
``jax.random.split``, each processed request consumes two (select, best) —
so the batched path is testable against the sequential reference path
(``tests/test_serve.py``).

Shape buckets: ``bucket_n`` rounds a task's N up to a quantum, zero-padding
the prediction tensor and marking the padded items as already-labeled via
the selectors' shared ``unlabeled`` mask, so near-shaped tasks share one
compiled program. The default quantum of 1 keeps shapes exact — padding
perturbs nothing selectable, but changes XLA reduction extents, which
forfeits the bitwise-parity guarantee; it is an opt-in compile-count lever.
"""

from __future__ import annotations

import secrets
import threading
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Optional

import numpy as np


class SlabFull(RuntimeError):
    """Admission refused: every slot of the bucket's slab is live."""


class UnknownSession(KeyError):
    """No live session with that id."""


# ---------------------------------------------------------------------------
# selector specs: a picklable/hashable description of a selector config
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SelectorSpec:
    """Method name + hyperparams as a hashable bucket-key component.

    ``kwargs`` is a sorted tuple of (name, value) pairs so equal configs
    compare equal (dicts don't hash); use :meth:`create` to build one.
    """

    method: str = "coda"
    kwargs: tuple = ()

    @classmethod
    def create(cls, method: str = "coda", **kwargs) -> "SelectorSpec":
        return cls(method=method, kwargs=tuple(sorted(kwargs.items())))

    def factory(self):
        """``preds -> Selector`` (the cli.build_selector_factory contract,
        minus the argparse namespace)."""
        from coda_tpu.losses import LOSS_FNS
        from coda_tpu.selectors import (
            CODAHyperparams,
            SELECTOR_FACTORIES,
            make_coda,
            make_modelpicker,
        )

        kw = dict(self.kwargs)
        if self.method.startswith("coda"):
            hp = CODAHyperparams(**kw)
            return lambda preds: make_coda(preds, hp, name=self.method)
        if self.method == "model_picker":
            return lambda preds: make_modelpicker(preds, **kw)
        if self.method not in SELECTOR_FACTORIES:
            raise ValueError(f"unknown serve method {self.method!r}")
        if "loss" in kw:  # risk-readout methods take a loss_fn callable
            kw["loss_fn"] = LOSS_FNS[kw.pop("loss")]
        return lambda preds: SELECTOR_FACTORIES[self.method](preds, **kw)


# ---------------------------------------------------------------------------
# the masked batch step
# ---------------------------------------------------------------------------

class SlotRequest(NamedTuple):
    """Per-slot inputs of one dispatch (leading axis = slot)."""

    pending: Any    # (S,) bool — does this slot have a request this tick?
    do_update: Any  # (S,) bool — apply the oracle label before selecting?
    idx: Any        # (S,) int32 — labeled item (only read when do_update)
    label: Any      # (S,) int32 — its oracle class
    prob: Any       # (S,) float32 — the selection prob the label was drawn at


class SlotResult(NamedTuple):
    """Per-slot outputs of one dispatch (leading axis = slot)."""

    next_idx: Any    # (S,) int32 — next most-informative item
    next_prob: Any   # (S,) float32 — its selection probability / q-value
    best: Any        # (S,) int32 — current best-model estimate
    stochastic: Any  # (S,) bool — did RNG affect this slot's step?


def _tree_where(flag, new, old):
    """Per-slot masked carry: ``new`` where ``flag`` (a scalar bool inside
    the slot vmap), else ``old``. None leaves (CODA's optional caches) must
    be None in both."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda a, b: jnp.where(flag, a, b), new, old)


def make_slab_step(selector, impl: Optional[str] = None):
    """The bucket's one compiled program: masked update+select+best over
    the slot axis.

    Per slot:  ``(state, key, request) -> (state', key', SlotResult)``.
    Slots without a pending request run the same computation (the price of a
    single program) but carry their state AND key through unchanged, so an
    idle session's stream of randomness is untouched — that is what makes a
    slab session replayable against the sequential reference path. Key
    consumption per processed request matches ``InteractiveSelector``'s
    drive pattern exactly: one split for ``select``, one for ``best``.

    Two lowerings of the same step (the ``modelpicker._bucket_sums``
    pattern), both a SINGLE jitted program per dispatch:

      * ``vmap`` — slots as a batch axis; the parallel-hardware lowering.
        Batched contractions may reassociate float accumulation, so scores
        can drift ~1e-7 from the sequential reference (selected indices and
        best-model answers measured identical; pinned against ``map`` by
        ``test_serve_vmap_matches_map``).
      * ``map`` — ``lax.map`` over slots: each slot runs the UNBATCHED
        per-session graph, which keeps results bitwise-identical to the
        sequential ``InteractiveSelector`` path (the parity test), at the
        cost of serializing slots within the dispatch.

    ``impl=None`` resolves by backend at build time: ``map`` on CPU (where
    serialized slots cost nothing and serving hosts want reference
    numerics), ``vmap`` on TPU/GPU (where the slot axis feeds the parallel
    units).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if impl is None:
        impl = "map" if jax.default_backend() == "cpu" else "vmap"
    if impl not in ("vmap", "map"):
        raise ValueError(f"unknown slab-step impl {impl!r} "
                         "(use 'vmap' or 'map')")

    def one(state0, key0, req):
        # masked oracle update: compute unconditionally (every slot runs one
        # program), keep only where requested
        updated = selector.update(
            state0, req.idx, req.label, req.prob)
        state1 = _tree_where(req.do_update, updated, state0)
        # the reference key choreography (protocol.InteractiveSelector):
        # _next_key() for select, _next_key() for best
        key1, k_sel = jax.random.split(key0)
        key2, k_best = jax.random.split(key1)
        res = selector.select(state1, k_sel)
        best, b_stoch = selector.best(state1, k_best)
        state_out = _tree_where(req.pending, state1, state0)
        key_out = jnp.where(req.pending, key2, key0)
        return state_out, key_out, SlotResult(
            next_idx=res.idx.astype(jnp.int32),
            next_prob=res.prob.astype(jnp.float32),
            best=best.astype(jnp.int32),
            stochastic=res.stochastic | b_stoch,
        )

    if impl == "map":
        return lambda states, keys, reqs: lax.map(
            lambda t: one(*t), (states, keys, reqs))
    return jax.vmap(one)


def _deactivate_padded(state, n_valid: int):
    """Mark a padded task's phantom items as already labeled.

    Every selector state in this framework exposes the ``(N,) bool``
    ``unlabeled`` mask (protocol convention), which is the single point all
    ``select`` candidate sets pass through — clearing the padded tail makes
    the padding unselectable without touching any method's math."""
    import jax.numpy as jnp

    if not hasattr(state, "unlabeled"):
        raise ValueError(
            f"selector state {type(state).__name__} has no 'unlabeled' "
            "mask; shape-padded buckets (bucket_n > 1) need it to disable "
            "the padded items — use bucket_n=1 for this method"
        )
    N = state.unlabeled.shape[0]
    return state._replace(
        unlabeled=state.unlabeled & (jnp.arange(N) < n_valid))


# ---------------------------------------------------------------------------
# bucket: one slab + its compiled step
# ---------------------------------------------------------------------------

class Bucket:
    """Fixed-capacity slab of selector carries for one (task, spec) pair.

    The selector is built ONCE from the bucket's concrete (padded)
    prediction tensor, so its statics (hard argmax preds, consensus
    pseudo-labels, Dirichlet priors) are computed at bucket creation — not
    re-derived inside every dispatch — and the jitted step's numerics are
    those of the reference ``InteractiveSelector`` path, which also jits
    closures over a concrete tensor. The tensor is therefore baked into the
    executable as a constant (fine at interactive-task scale; the
    preds-as-argument pattern of ``engine/loop.py`` is the move if a served
    task ever approaches HBM capacity).
    """

    def __init__(self, preds, spec: SelectorSpec, capacity: int,
                 n_valid: Optional[int] = None, task: str = "",
                 step_impl: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        self.task = task
        self.spec = spec
        self.capacity = int(capacity)
        # serializes this bucket's slab swaps: allocate/release and the
        # batcher's dispatch functionally replace the slab arrays, but only
        # against each other — other buckets never contend on it
        self.lock = threading.RLock()
        self.preds = jnp.asarray(preds)
        H, N, C = self.preds.shape
        self.shape = (H, N, C)
        self.n_valid = N if n_valid is None else int(n_valid)
        self.n_classes = C
        self.selector = spec.factory()(self.preds)
        self._init = jax.jit(self.selector.init)
        self._step = jax.jit(make_slab_step(self.selector, impl=step_impl))
        get_pbest = self.selector.extras.get("get_pbest")
        self._get_pbest = None if get_pbest is None else jax.jit(get_pbest)
        # the slab: state pytree with a leading (capacity,) slot axis. All
        # slots start from init(key=0) — real sessions overwrite their slot
        # at admission, so the filler only fixes shapes/dtypes.
        dummy = jnp.zeros((self.capacity, 2), jnp.uint32)
        self.states = jax.jit(jax.vmap(self.selector.init))(dummy)
        self.keys = jnp.zeros((self.capacity, 2), jnp.uint32)
        # LIFO free list: a just-closed slot is the next one reused, which
        # keeps the slab's live region dense and is trivially testable
        self._free = list(range(self.capacity - 1, -1, -1))

    # -- slot lifecycle (caller holds this bucket's lock) ------------------
    def allocate(self, seed: int) -> int:
        import jax
        import jax.numpy as jnp

        if not self._free:
            raise SlabFull(
                f"bucket {self.task}/{self.spec.method}: all "
                f"{self.capacity} slots live")
        slot = self._free.pop()
        # reference key stream: PRNGKey(seed); init() consumes one split
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        state = self._init(sub)
        if self.n_valid < self.shape[1]:
            state = _deactivate_padded(state, self.n_valid)
        self.states = jax.tree.map(
            lambda slab, x: slab.at[slot].set(x), self.states, state)
        self.keys = self.keys.at[slot].set(key.astype(jnp.uint32))
        return slot

    def release(self, slot: int) -> None:
        self._free.append(slot)

    @property
    def live(self) -> int:
        return self.capacity - len(self._free)

    # -- the dispatch (batcher thread, holding this bucket's lock) ---------
    def dispatch(self, requests: dict) -> dict:
        """Run ONE compiled masked step over the whole slab.

        ``requests``: slot -> dict(do_update, idx, label, prob). Every slot
        executes; only requesting slots advance state/keys and get a result
        row back. Returns slot -> result dict (host scalars)."""
        import jax
        import jax.numpy as jnp

        S = self.capacity
        pending = np.zeros(S, bool)
        do_update = np.zeros(S, bool)
        idx = np.zeros(S, np.int32)
        label = np.zeros(S, np.int32)
        prob = np.zeros(S, np.float32)
        for slot, r in requests.items():
            pending[slot] = True
            do_update[slot] = bool(r.get("do_update", False))
            idx[slot] = r.get("idx", 0)
            label[slot] = r.get("label", 0)
            prob[slot] = r.get("prob", 0.0)
        req = SlotRequest(
            pending=jnp.asarray(pending), do_update=jnp.asarray(do_update),
            idx=jnp.asarray(idx), label=jnp.asarray(label),
            prob=jnp.asarray(prob))
        self.states, self.keys, out = self._step(self.states, self.keys, req)
        out = jax.tree.map(np.asarray, out)  # one host sync for the batch
        return {
            slot: {
                "next_idx": int(out.next_idx[slot]),
                "next_prob": float(out.next_prob[slot]),
                "best": int(out.best[slot]),
                "stochastic": bool(out.stochastic[slot]),
            }
            for slot in requests
        }

    # -- cheap per-session reads ------------------------------------------
    def slot_state(self, slot: int):
        import jax

        return jax.tree.map(lambda x: x[slot], self.states)

    def pbest(self, slot: int):
        """P(model is best) for one slot, when the method exposes it (CODA's
        ``get_pbest`` extra) — the cheap posterior read behind GET /best."""
        if self._get_pbest is None:
            return None
        return np.asarray(self._get_pbest(self.slot_state(slot)))


# ---------------------------------------------------------------------------
# session store
# ---------------------------------------------------------------------------

@dataclass
class Session:
    """Host-side record of one live interactive session."""

    sid: str
    task: str
    bucket: Bucket
    slot: int
    seed: int
    n_labeled: int = 0
    last: dict = field(default_factory=dict)  # most recent SlotResult row


def _round_up(n: int, quantum: int) -> int:
    return ((n + quantum - 1) // quantum) * quantum


class SessionStore:
    """Multiplexes sessions onto per-(task, spec, shape) slabs.

    ``capacity`` bounds EACH bucket's slab (admission past it raises
    :class:`SlabFull` — the server's 503). ``bucket_n`` is the N-padding
    quantum (see module docstring; 1 = exact shapes).
    Thread safety, three tiers so one bucket's work never stalls another's:
    the store lock guards only the host dicts (tasks/buckets/sessions —
    microseconds); each BUCKET's lock serializes that bucket's slab swaps
    (admission writes vs. the batcher's dispatch; admission to a busy
    bucket waits out at most one in-flight dispatch — fine, since session
    creation itself needs a dispatch to learn its first item); and bucket
    CONSTRUCTION (selector statics + init compile, potentially seconds)
    runs under a dedicated build lock with no other lock held, so standing
    traffic keeps flowing while a new (task, spec) warms up.
    """

    def __init__(self, capacity: int = 64, bucket_n: int = 1,
                 step_impl: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if bucket_n < 1:
            raise ValueError("bucket_n must be >= 1")
        self.capacity = capacity
        self.bucket_n = bucket_n
        self.step_impl = step_impl
        self._tasks: dict[str, Any] = {}     # name -> (H, N, C) ndarray
        self._meta: dict[str, dict] = {}     # name -> class/model names
        self._buckets: dict[tuple, Bucket] = {}
        self._sessions: dict[str, Session] = {}
        self.lock = threading.RLock()
        self._build_lock = threading.Lock()

    # -- tasks -------------------------------------------------------------
    def register_task(self, name: str, preds, class_names=None,
                      model_names=None) -> None:
        preds = np.asarray(preds, np.float32)
        if preds.ndim != 3:
            raise ValueError(f"preds must be (H, N, C), got {preds.shape}")
        with self.lock:
            self._tasks[name] = preds
            H, N, C = preds.shape
            self._meta[name] = {
                "class_names": list(class_names
                                    or [f"class {c}" for c in range(C)]),
                "model_names": list(model_names
                                    or [f"model {h}" for h in range(H)]),
            }

    def tasks(self) -> list[str]:
        with self.lock:
            return sorted(self._tasks)

    def task_meta(self, name: str) -> dict:
        with self.lock:
            return dict(self._meta[name])

    def _bucket_for(self, task: str, spec: SelectorSpec) -> Bucket:
        with self.lock:
            preds = self._tasks[task]
        H, N, C = preds.shape
        n_pad = _round_up(N, self.bucket_n)
        key = (task, spec, (H, n_pad, C))
        with self.lock:
            b = self._buckets.get(key)
        if b is not None:
            return b
        # the expensive part (selector statics, init compile) runs with no
        # store/bucket lock held, so live traffic is untouched; the build
        # lock just keeps two threads from compiling the same bucket twice
        with self._build_lock:
            with self.lock:
                b = self._buckets.get(key)
            if b is not None:
                return b
            if n_pad != N:
                preds = np.pad(preds, ((0, 0), (0, n_pad - N), (0, 0)))
            b = Bucket(preds, spec, self.capacity, n_valid=N, task=task,
                       step_impl=self.step_impl)
            with self.lock:
                self._buckets[key] = b
            return b

    # -- sessions ----------------------------------------------------------
    def open(self, task: str, spec: SelectorSpec, seed: int = 0) -> Session:
        with self.lock:
            if task not in self._tasks:
                raise KeyError(f"unknown task {task!r}; registered: "
                               f"{self.tasks()}")
        bucket = self._bucket_for(task, spec)
        with bucket.lock:
            slot = bucket.allocate(seed)  # raises SlabFull when exhausted
        sess = Session(sid=secrets.token_hex(8), task=task,
                       bucket=bucket, slot=slot, seed=seed)
        with self.lock:
            self._sessions[sess.sid] = sess
        return sess

    def get(self, sid: str) -> Session:
        with self.lock:
            sess = self._sessions.get(sid)
            if sess is None:
                raise UnknownSession(sid)
            return sess

    def alive(self, sid: str) -> bool:
        with self.lock:
            return sid in self._sessions

    def close(self, sid: str) -> None:
        with self.lock:
            sess = self._sessions.pop(sid, None)
        if sess is None:
            raise UnknownSession(sid)
        with sess.bucket.lock:
            sess.bucket.release(sess.slot)

    def live_sessions(self) -> int:
        with self.lock:
            return len(self._sessions)

    def buckets(self) -> list[Bucket]:
        with self.lock:
            return list(self._buckets.values())
