"""Session slabs: many interactive selection sessions as one vmapped carry.

``demo/app.py`` drives exactly one ``InteractiveSelector`` per user, paying a
host↔device round trip per click. But the paper's loop (score → pick →
oracle label → posterior update → best) is embarrassingly batchable across
independent sessions — the same insight that makes seeds a ``vmap`` axis in
``engine/loop.py``. This module holds the device-side half of the serving
layer:

  * a **bucket** is a fixed-capacity slab of selector carries for one
    (task, selector-config) pair: the state pytree with a leading SLOT axis,
    a per-slot PRNG key array, and a host-side free list. One jit-compiled
    **masked step** (update-if-requested + select + best, ``vmap`` over
    slots) serves every session in the bucket per dispatch;
  * the **SessionStore** multiplexes sessions onto buckets: admission takes
    a free slot (or refuses — the backpressure signal the server turns into
    HTTP 503), close returns the slot for reuse.

Key-stream parity: a session's randomness is bit-identical to driving one
``InteractiveSelector(selector, seed)`` by hand — init consumes one
``jax.random.split``, each processed request consumes two (select, best) —
so the batched path is testable against the sequential reference path
(``tests/test_serve.py``).

Shape buckets: ``bucket_n`` rounds a task's N up to a quantum, zero-padding
the prediction tensor and marking the padded items as already-labeled via
the selectors' shared ``unlabeled`` mask, so near-shaped tasks share one
compiled program. The default quantum of 1 keeps shapes exact — padding
perturbs nothing selectable, but changes XLA reduction extents, which
forfeits the bitwise-parity guarantee; it is an opt-in compile-count lever.
"""

from __future__ import annotations

import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Optional

import numpy as np


class SlabFull(RuntimeError):
    """Admission refused: every slot of the bucket's slab is live."""


class UnknownSession(KeyError):
    """No live session with that id."""


class BucketQuarantined(RuntimeError):
    """The bucket's slab was lost to a step failure and is being rebuilt
    from its sessions' recorder streams (``serve/recovery.py``) — retry
    shortly. Distinct from the terminal ``failed`` state, which only a
    digest mismatch or exhausted heal retries produces."""


class StaleOwner(RuntimeError):
    """The fencing rejection: a verb arrived stamped with an ownership
    epoch NEWER than this replica's copy of the session — the session
    migrated away and this copy survived (a healed partition, a crash
    restore of an unsealed stream). Committing here would double-apply
    against the copy the new owner holds, so the verb is refused and the
    router re-locates. Structural, not probabilistic: the split-brain
    double-apply is impossible while every routed verb carries the
    router's epoch."""

    def __init__(self, sid: str, have: int, want: int):
        super().__init__(
            f"session {sid}: this replica's copy is at ownership epoch "
            f"{have} but the verb was fenced at epoch {want} — the "
            "session migrated away; re-locate and retry")
        self.sid = sid
        self.have = int(have)
        self.want = int(want)


# ---------------------------------------------------------------------------
# selector specs: a picklable/hashable description of a selector config
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SelectorSpec:
    """Method name + hyperparams as a hashable bucket-key component.

    ``kwargs`` is a sorted tuple of (name, value) pairs so equal configs
    compare equal (dicts don't hash); use :meth:`create` to build one.

    ``acq_batch`` is the labels-per-round width of the bucket's compiled
    step (the serving face of ``--acq-batch``): a q > 1 bucket's slab
    step applies q oracle answers per slot through one fused update and
    proposes the next q points per round, so sessions at different q
    never share an executable. Part of the spec (not ``kwargs``) because
    it is an ENGINE knob, not a selector hyperparameter.
    """

    method: str = "coda"
    kwargs: tuple = ()
    acq_batch: int = 1

    @classmethod
    def create(cls, method: str = "coda", acq_batch: int = 1,
               **kwargs) -> "SelectorSpec":
        if int(acq_batch) < 1:
            raise ValueError(f"acq_batch must be >= 1, got {acq_batch}")
        return cls(method=method, kwargs=tuple(sorted(kwargs.items())),
                   acq_batch=int(acq_batch))

    def factory(self):
        """``preds -> Selector`` (the cli.build_selector_factory contract,
        minus the argparse namespace)."""
        from coda_tpu.losses import LOSS_FNS
        from coda_tpu.selectors import (
            CODAHyperparams,
            SELECTOR_FACTORIES,
            make_coda,
            make_modelpicker,
        )

        kw = dict(self.kwargs)
        if self.method.startswith("coda"):
            hp = CODAHyperparams(**kw)
            return lambda preds: make_coda(preds, hp, name=self.method)
        if self.method == "model_picker":
            return lambda preds: make_modelpicker(preds, **kw)
        if self.method not in SELECTOR_FACTORIES:
            raise ValueError(f"unknown serve method {self.method!r}")
        if "loss" in kw:  # risk-readout methods take a loss_fn callable
            kw["loss_fn"] = LOSS_FNS[kw.pop("loss")]
        return lambda preds: SELECTOR_FACTORIES[self.method](preds, **kw)


# ---------------------------------------------------------------------------
# the masked batch step
# ---------------------------------------------------------------------------

class SlotRequest(NamedTuple):
    """Per-slot inputs of one dispatch (leading axis = slot)."""

    pending: Any    # (S,) bool — does this slot have a request this tick?
    do_update: Any  # (S,) bool — apply the oracle label before selecting?
    idx: Any        # (S,) int32 — labeled item (only read when do_update)
    label: Any      # (S,) int32 — its oracle class
    prob: Any       # (S,) float32 — the selection prob the label was drawn at


class SlotResult(NamedTuple):
    """Per-slot outputs of one dispatch (leading axis = slot)."""

    next_idx: Any    # (S,) int32 — next most-informative item
    next_prob: Any   # (S,) float32 — its selection probability / q-value
    best: Any        # (S,) int32 — current best-model estimate
    stochastic: Any  # (S,) bool — did RNG affect this slot's step?
    # P(best) posterior digest of the post-update state (NaN when the
    # method exposes no ``get_pbest``): the same (max, entropy-bits) pair
    # the flight recorder captures per round. Computed INSIDE the one
    # compiled step — no extra dispatch — it is what makes restored /
    # healed sessions verifiable bitwise against their recorder streams.
    pbest_max: Any      # (S,) float32
    pbest_entropy: Any  # (S,) float32


def _tree_where(flag, new, old):
    """Per-slot masked carry: ``new`` where ``flag`` (a scalar bool inside
    the slot vmap), else ``old``. None leaves (CODA's optional caches) must
    be None in both."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda a, b: jnp.where(flag, a, b), new, old)


def make_slab_step(selector, impl: Optional[str] = None):
    """The bucket's one compiled program: masked update+select+best over
    the slot axis.

    Per slot:  ``(state, key, request) -> (state', key', SlotResult)``.
    Slots without a pending request run the same computation (the price of a
    single program) but carry their state AND key through unchanged, so an
    idle session's stream of randomness is untouched — that is what makes a
    slab session replayable against the sequential reference path. Key
    consumption per processed request matches ``InteractiveSelector``'s
    drive pattern exactly: one split for ``select``, one for ``best``.

    Two lowerings of the same step (the ``modelpicker._bucket_sums``
    pattern), both a SINGLE jitted program per dispatch:

      * ``vmap`` — slots as a batch axis; the parallel-hardware lowering.
        Batched contractions may reassociate float accumulation, so scores
        can drift ~1e-7 from the sequential reference (selected indices and
        best-model answers measured identical; pinned against ``map`` by
        ``test_serve_vmap_matches_map``).
      * ``map`` — ``lax.map`` over slots: each slot runs the UNBATCHED
        per-session graph, which keeps results bitwise-identical to the
        sequential ``InteractiveSelector`` path (the parity test), at the
        cost of serializing slots within the dispatch.

    ``impl=None`` resolves by backend at build time: ``map`` on CPU (where
    serialized slots cost nothing and serving hosts want reference
    numerics), ``vmap`` on TPU/GPU (where the slot axis feeds the parallel
    units).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if impl is None:
        impl = "map" if jax.default_backend() == "cpu" else "vmap"
    if impl not in ("vmap", "map"):
        raise ValueError(f"unknown slab-step impl {impl!r} "
                         "(use 'vmap' or 'map')")

    from coda_tpu.ops.masked import entropy2

    get_pbest = selector.extras.get("get_pbest")

    def one(state0, key0, req):
        # masked oracle update: compute unconditionally (every slot runs one
        # program), keep only where requested
        updated = selector.update(
            state0, req.idx, req.label, req.prob)
        state1 = _tree_where(req.do_update, updated, state0)
        # the reference key choreography (protocol.InteractiveSelector):
        # _next_key() for select, _next_key() for best
        key1, k_sel = jax.random.split(key0)
        key2, k_best = jax.random.split(key1)
        res = selector.select(state1, k_sel)
        best, b_stoch = selector.best(state1, k_best)
        # posterior digest of the post-update state (mirrors the flight
        # recorder's per-round pbest_max/pbest_entropy capture exactly)
        if get_pbest is not None:
            pb = get_pbest(state1).astype(jnp.float32)
            d_max, d_ent = pb.max(), entropy2(pb)
        else:
            d_max = jnp.asarray(jnp.nan, jnp.float32)
            d_ent = jnp.asarray(jnp.nan, jnp.float32)
        state_out = _tree_where(req.pending, state1, state0)
        key_out = jnp.where(req.pending, key2, key0)
        return state_out, key_out, SlotResult(
            next_idx=res.idx.astype(jnp.int32),
            next_prob=res.prob.astype(jnp.float32),
            best=best.astype(jnp.int32),
            stochastic=res.stochastic | b_stoch,
            pbest_max=d_max,
            pbest_entropy=d_ent,
        )

    if impl == "map":
        return lambda states, keys, reqs: lax.map(
            lambda t: one(*t), (states, keys, reqs))
    return jax.vmap(one)


def _deactivate_padded(state, n_valid: int):
    """Mark a padded task's phantom items as already labeled.

    Every selector state in this framework exposes the ``(N,) bool``
    ``unlabeled`` mask (protocol convention), which is the single point all
    ``select`` candidate sets pass through — clearing the padded tail makes
    the padding unselectable without touching any method's math."""
    import jax.numpy as jnp

    if not hasattr(state, "unlabeled"):
        raise ValueError(
            f"selector state {type(state).__name__} has no 'unlabeled' "
            "mask; shape-padded buckets (bucket_n > 1) need it to disable "
            "the padded items — use bucket_n=1 for this method"
        )
    N = state.unlabeled.shape[0]
    return state._replace(
        unlabeled=state.unlabeled & (jnp.arange(N) < n_valid))


# ---------------------------------------------------------------------------
# bucket: one slab + its compiled step
# ---------------------------------------------------------------------------

class Bucket:
    """Fixed-capacity slab of selector carries for one (task, spec) pair.

    The selector is built ONCE from the bucket's concrete (padded)
    prediction tensor, so its statics (hard argmax preds, consensus
    pseudo-labels, Dirichlet priors) are computed at bucket creation — not
    re-derived inside every dispatch — and the jitted step's numerics are
    those of the reference ``InteractiveSelector`` path, which also jits
    closures over a concrete tensor. The tensor is therefore baked into the
    executable as a constant (fine at interactive-task scale; the
    preds-as-argument pattern of ``engine/loop.py`` is the move if a served
    task ever approaches HBM capacity).
    """

    def __init__(self, preds, spec: SelectorSpec, capacity: int,
                 n_valid: Optional[int] = None, task: str = "",
                 step_impl: Optional[str] = None, donate: bool = True,
                 faults=None, registry=None):
        import jax
        import jax.numpy as jnp

        self.task = task
        self.spec = spec
        self.capacity = int(capacity)
        self.step_impl = step_impl  # as requested (None = backend default)
        # serializes this bucket's slab ACCESS (the batcher's dispatch,
        # posterior reads) — allocate/release never take it; they stage
        # writes under _host_lock instead (see allocate). Other buckets
        # never contend on it.
        self.lock = threading.RLock()
        self.preds = jnp.asarray(preds)
        H, N, C = self.preds.shape
        self.shape = (H, N, C)
        self.n_valid = N if n_valid is None else int(n_valid)
        self.n_classes = C
        # batch-label buckets (spec.acq_batch > 1) compile the q-wide
        # selector: select proposes (q,) points per round, update applies
        # (q,) answers as one fused multi-row posterior update — the slab
        # step and every downstream read are shape-generic, so nothing
        # else here knows about q beyond the request/result marshaling
        self.acq_batch = max(1, int(getattr(spec, "acq_batch", 1)))
        # the surrogate scorer's warmup/fallback lax.cond lowers to a
        # SELECT under the vmap slab step (both branches execute per slot
        # per round), so on a vmap-lowered slab the rung costs a full
        # exact pass PLUS the surrogate machinery — strictly slower than
        # eig_scorer='exact'. Loud, once per bucket: an operator reading
        # healthy surrogate counters on /metrics must not conclude the
        # rung is amortizing anything there.
        _scorer = dict(getattr(spec, "kwargs", ()) or ()).get(
            "eig_scorer", "exact")
        if _scorer != "exact":
            import jax as _jax

            _impl = step_impl or (
                "map" if _jax.default_backend() == "cpu" else "vmap")
            if _impl == "vmap":
                import sys as _sys

                print(
                    f"bucket {task}/{spec.method}: eig_scorer={_scorer!r} "
                    "under the 'vmap' slab lowering runs BOTH cond "
                    "branches per slot (full exact pass + surrogate "
                    "machinery — no amortization; correctness and the "
                    "contract still hold). Use --step-impl map or "
                    "eig_scorer='exact' on this backend.",
                    file=_sys.stderr)
        base_selector = spec.factory()(self.preds)
        if self.acq_batch > 1:
            from coda_tpu.selectors.batch import make_batched_selector

            self.selector = make_batched_selector(base_selector,
                                                  self.acq_batch)
        else:
            self.selector = base_selector
        self._init = jax.jit(self.selector.init)
        # donated slab buffers: the step's (states, keys) carry is updated
        # in place instead of allocating a fresh slab copy per tick (the
        # per-tick copy was measurable at serving rates). Donation only
        # changes buffer lifetime, never numerics — pinned bitwise against
        # the undonated path by test_serve_donated_step_bitwise. donate=False
        # keeps the copying path for that pin and for callers that alias
        # slab arrays across dispatches.
        self.donate = bool(donate)
        self._step = jax.jit(make_slab_step(self.selector, impl=step_impl),
                             donate_argnums=(0, 1) if self.donate else ())
        get_pbest = self.selector.extras.get("get_pbest")
        self._get_pbest = None if get_pbest is None else jax.jit(get_pbest)
        # admission writes a single slot row; donating the slab to the
        # writer makes that O(row) in place instead of an O(slab) copy —
        # at capacity 256 the copy was ~40 ms per admission, which turned
        # a 256-session thundering herd into a 10 s convoy
        if self.donate:
            def _write(states, keys, slot, state, key):
                return (jax.tree.map(lambda s, x: s.at[slot].set(x),
                                     states, state),
                        keys.at[slot].set(key))

            self._write_slot = jax.jit(_write, donate_argnums=(0, 1))
        else:
            self._write_slot = None
        # AOT warm pool (see warm()): compiled executables, used by
        # dispatch/allocate/pbest when present so the compile happens at
        # server start, not under the first user's click
        self._step_exec = None
        self._init_exec = None
        self._pbest_exec = None
        self._pbest_at = None
        self._write_exec = None
        # warm() probes whether init is key-independent (true for every
        # selector in this framework: priors/caches are deterministic
        # functions of preds); when it is, this caches the one init state
        # and admission skips the init executable entirely — the
        # thundering-herd lever (CODA's init fills the incremental P(best)
        # cache, ~11 update-steps of compute per admission otherwise)
        self._init_state = None
        self.warm_s: Optional[float] = None   # wall seconds spent in warm()
        # per-executable XLA cost attribution of the warm pool, harvested
        # by warm() (telemetry/costs.py): program name -> {flops,
        # bytes_accessed, peak_hbm_bytes, roofline_class, ...}. Surfaced
        # per bucket on /stats and as executable_* gauges on /metrics —
        # "the tick is one capacity-bound slab step" as a machine-read
        # field instead of a NOTES sentence.
        self.cost_info: dict = {}
        self._n_warm = 0      # executables the last successful warm() built
        self.warm_hits = 0    # dispatches served by the AOT executable
        self.warm_misses = 0  # dispatches that fell back to lazy jit
        # a step failure that consumed donated carries loses the slab.
        # ``quarantined`` marks the slab lost-but-healable: recovery
        # rebuilds it by replaying every live session's recorder stream
        # (serve/recovery.py) and clears the mark on digest-verified
        # success. ``failed`` stays the TERMINAL state — only a digest
        # mismatch during heal or exhausted heal retries set it — so
        # later dispatches/admissions fail loudly and attributably
        # instead of with 'Array has been deleted'.
        self.failed: Optional[str] = None
        self.quarantined: Optional[str] = None
        self.heals = 0           # successful slab rebuilds (stats evidence)
        self._faults = faults    # optional FaultInjector (serve/faults.py)
        # telemetry registry the warm-pool cost gauges land in (None =
        # the process-global one); the app threads its own through the
        # store so /metrics renders the costs of ITS buckets
        self._registry = registry
        # standalone posterior-digest read (built lazily in digest()):
        # mirrors the in-step digest so an imported snapshot verifies
        # against the stream's last recorded digest without a dispatch
        self._digest_fn = None
        # cross-session surrogate prior (serve/priors.py): the admission-
        # time applied-prior record {"digest", "credit", <prior_to_dict
        # fields>} or None. set_prior installs/clears it; admission seeds
        # every NEW session's fit from it (restore paths re-apply the
        # per-session RECORDED prior instead — the pool may have evolved
        # since, and replay must reproduce the admitted init bitwise).
        self.prior: Optional[dict] = None
        # cached flat-leaf indices of the surrogate fit's (A, b, n,
        # rounds) within the state pytree — fit_from_leaves' map, built
        # lazily (the demote-time pool contribution reads host leaves
        # the sweeper already materialized; no extra device sync)
        self._fit_leaf_idx: Optional[dict] = None
        self.last_timing: dict = {}  # per-dispatch phase wall times
        # the slab: state pytree with a leading (capacity,) slot axis. All
        # slots start from init(key=0) — real sessions overwrite their slot
        # at admission, so the filler only fixes shapes/dtypes. Kept as a
        # bound jit so the heal path can reallocate a fresh slab without
        # re-tracing (reset_slab).
        self._slab_init = jax.jit(jax.vmap(self.selector.init))
        dummy = jnp.zeros((self.capacity, 2), jnp.uint32)
        self.states = self._slab_init(dummy)
        self.keys = jnp.zeros((self.capacity, 2), jnp.uint32)
        # LIFO free list: a just-closed slot is the next one reused, which
        # keeps the slab's live region dense and is trivially testable.
        # The free list and the staged-write buffer live under their own
        # cheap host lock so admission/close NEVER wait out an in-flight
        # dispatch (which holds ``self.lock`` for a full slab step — at
        # high capacity that made a thundering herd of opens a convoy).
        self._host_lock = threading.Lock()
        self._free = list(range(self.capacity - 1, -1, -1))
        # admission writes staged here as (slot, state, key), applied to
        # the slab under ``self.lock`` at the next slab access (dispatch /
        # slot_state) — the only writers of the slab arrays are therefore
        # lock holders, while allocate() itself only computes the init
        # state (no slab access at all)
        self._staged: list = []

    # -- AOT warm-up -------------------------------------------------------
    @property
    def is_warm(self) -> bool:
        return self._step_exec is not None

    def warm(self) -> dict:
        """Ahead-of-time compile this bucket's executables.

        ``jit(...).lower().compile()`` for the masked slab step, the
        per-slot init, and the pbest read — the three programs a session's
        lifetime touches — so first-hit compilation never lands under live
        traffic. With a persistent compilation cache directory configured
        (``--compilation-cache-dir``), a restarted server deserializes
        these instead of recompiling (0 fresh backend compiles — asserted
        by the warm-restart test via the persistent-cache miss counter).

        Runs under the bucket (dispatch) lock: a background warm-up racing
        live traffic (``start(warm_async=True)`` with clients that ignore
        the readiness gate) must not read slab buffers a donating dispatch
        is invalidating. Early dispatches therefore serialize behind the
        warm-up — the same wait they would have spent lazily compiling.
        Idempotent and retryable: the compiled executables are published
        atomically at the end, so a mid-compile failure leaves the bucket
        fully cold. Returns {executables, seconds}.
        """
        import time as _time

        import jax
        import jax.numpy as jnp

        with self.lock:
            if self.is_warm:
                return {"executables": self._n_warm,
                        "seconds": self.warm_s or 0.0}
            t0 = _time.perf_counter()
            S = self.capacity
            lane = (S,) if self.acq_batch == 1 else (S, self.acq_batch)
            req = SlotRequest(
                pending=jnp.zeros(S, bool), do_update=jnp.zeros(S, bool),
                idx=jnp.zeros(lane, jnp.int32),
                label=jnp.zeros(lane, jnp.int32),
                prob=jnp.zeros(lane, jnp.float32))
            # NOTE: after lower().compile(), dispatch must call the
            # RETURNED executable — calling the jit-wrapped function again
            # would trace and compile a second, separate program
            step_exec = self._step.lower(self.states, self.keys,
                                         req).compile()
            init_exec = self._init.lower(jnp.zeros(2, jnp.uint32)).compile()
            n = 2
            pbest_exec = write_exec = None
            if self._get_pbest is not None:
                pbest_exec = self._get_pbest.lower(
                    self.slot_state(0)).compile()
                n += 1
                # the standalone digest read too: it is the wake-from-warm
                # fast path's verification (serve/tiering.py), and a lazy
                # first-use compile there would land inside some user's
                # first wake instead of the warm-up
                self.digest(0)
                n += 1
            if self._write_slot is not None:
                write_exec = self._write_slot.lower(
                    self.states, self.keys, jnp.int32(0),
                    self.slot_state(0), jnp.zeros(2, jnp.uint32)).compile()
                n += 1
            # key-independence probe: if init ignores its key (true for
            # every selector here — the state is a deterministic function
            # of preds), two distinct keys produce bitwise-identical
            # states, and admission can reuse ONE cached init state
            # instead of re-running the init executable per session. The
            # PRNG choreography is untouched: the session's key stream
            # still consumes its init split.
            s_a = init_exec(jnp.zeros(2, jnp.uint32))
            s_b = init_exec(
                jnp.asarray([0x9e3779b9, 0x85ebca6b], jnp.uint32))
            init_state = None
            if all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
                   for x, y in zip(jax.tree.leaves(s_a),
                                   jax.tree.leaves(s_b))):
                if self.n_valid < self.shape[1]:
                    s_a = _deactivate_padded(s_a, self.n_valid)
                init_state = s_a
            # publish atomically (everything or nothing; is_warm keys off
            # _step_exec, so a failure above leaves the bucket retryable)
            # cost attribution of the pool: XLA's own analysis of each
            # freshly (de)serialized executable — the step program is the
            # bucket's steady-state cost; init/pbest/write are the
            # admission/read paths. Best-effort by contract: a backend
            # without cost_analysis leaves cost_info empty, never fails
            # the warm-up.
            from coda_tpu.telemetry.costs import harvest_executable_cost

            H_, N_, C_ = self.shape
            prefix = (f"serve/{self.task}/{self.spec.method}/"
                      f"{H_}x{N_}x{C_}")
            extra = {"task": self.task, "method": self.spec.method,
                     "shape": list(self.shape), "capacity": self.capacity}
            for pname, ex in (("step", step_exec), ("init", init_exec),
                              ("pbest", pbest_exec),
                              ("write_slot", write_exec)):
                if ex is None:
                    continue
                entry = harvest_executable_cost(
                    ex, f"{prefix}/{pname}", site="serve",
                    registry=self._registry,
                    extra=dict(extra, program=pname))
                if entry is not None:
                    self.cost_info[pname] = entry
            self._init_exec = init_exec
            self._pbest_exec = pbest_exec
            self._write_exec = write_exec
            self._init_state = init_state
            self.warm_s = _time.perf_counter() - t0
            self._n_warm = n
            self._step_exec = step_exec
            return {"executables": n, "seconds": self.warm_s}

    def _check_available(self) -> None:
        """Raise attributably when the slab cannot be touched."""
        if self.failed is not None:
            raise RuntimeError(
                f"bucket {self.task}/{self.spec.method} is failed "
                f"(restart to recover): {self.failed}")
        if self.quarantined is not None:
            raise BucketQuarantined(
                f"bucket {self.task}/{self.spec.method} is quarantined "
                f"(slab rebuild in progress, retry shortly): "
                f"{self.quarantined}")

    def _fresh_slot_state(self, seed: int, prior: Optional[dict] = None):
        """Reference-choreography ``(state, key)`` for a new session:
        ``PRNGKey(seed)``, init consumes one split (always — even when the
        cached key-independent init state makes its VALUE moot). Shared by
        admission and the heal/restore replay paths.

        ``prior`` (an applied-prior record — see ``set_prior``) seeds the
        state's carried surrogate fit from the cross-session pool: the
        regression sufficient statistics fold in and warmup credit is
        granted, everything else of the init stays the reference value.
        The caller owns replay consistency: a restore must pass the
        SAME record the session was admitted under."""
        import jax
        import jax.numpy as jnp

        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        if self._init_state is not None:
            state = self._init_state
        else:
            init = (self._init_exec if self._init_exec is not None
                    else self._init)
            state = init(sub.astype(jnp.uint32))
            if self.n_valid < self.shape[1]:
                state = _deactivate_padded(state, self.n_valid)
        if prior is not None and getattr(state, "surrogate",
                                         None) is not None:
            from coda_tpu.selectors.surrogate import (prior_from_dict,
                                                      seed_fit)

            state = state._replace(
                surrogate=seed_fit(state.surrogate,
                                   prior_from_dict(prior)))
        return state, key.astype(jnp.uint32)

    def set_prior(self, stats) -> Optional[dict]:
        """Install (or clear, with None) the pool prior new admissions
        seed from; returns the applied-prior record now in force."""
        from coda_tpu.selectors.surrogate import (prior_digest,
                                                  prior_to_dict,
                                                  prior_warmup_credit)

        if stats is None or getattr(stats, "n", 0) <= 0:
            self.prior = None
        else:
            rec = prior_to_dict(stats)
            rec["digest"] = prior_digest(stats)
            rec["credit"] = prior_warmup_credit(stats)
            self.prior = rec
        return self.prior

    # -- slot lifecycle (no bucket lock needed: slab writes are staged) ----
    def allocate(self, seed: int, prior: Optional[dict] = None) -> int:
        """Take a free slot and stage its freshly-initialized state.

        Runs WITHOUT the bucket (dispatch) lock: the init computation
        touches no slab array, and the produced (slot, state, key) row is
        staged for the next lock holder to apply — so admission latency is
        one init executable, never an in-flight slab step."""
        self._check_available()
        with self._host_lock:
            if not self._free:
                raise SlabFull(
                    f"bucket {self.task}/{self.spec.method}: all "
                    f"{self.capacity} slots live")
            slot = self._free.pop()
        state, key = self._fresh_slot_state(seed, prior=prior)
        with self._host_lock:
            self._staged.append((slot, state, key))
        return slot

    def release(self, slot: int) -> None:
        """Return a slot to the free list; drops any still-staged write for
        it (an open that aborted before its first dispatch). No dispatch
        lock: the slot's slab rows stay garbage until reallocation."""
        with self._host_lock:
            self._staged = [(s, st, k) for s, st, k in self._staged
                            if s != slot]
            self._free.append(slot)

    def _apply_staged(self) -> None:
        """Write staged admissions into the slab (caller holds ``lock``)."""
        import jax
        import jax.numpy as jnp

        with self._host_lock:
            staged, self._staged = self._staged, []
        for slot, state, key in staged:
            if self._write_slot is not None:
                write = (self._write_exec if self._write_exec is not None
                         else self._write_slot)
                self.states, self.keys = write(
                    self.states, self.keys, jnp.int32(slot), state, key)
            else:
                self.states = jax.tree.map(
                    lambda slab, x: slab.at[slot].set(x), self.states,
                    state)
                self.keys = self.keys.at[slot].set(key)

    @property
    def live(self) -> int:
        with self._host_lock:
            return self.capacity - len(self._free)

    # -- the dispatch (batcher thread, holding this bucket's lock) ---------
    def dispatch(self, requests: dict, _healing: bool = False) -> dict:
        """Run ONE compiled masked step over the whole slab.

        ``requests``: slot -> dict(do_update, idx, label, prob). Every slot
        executes; only requesting slots advance state/keys and get a result
        row back. Returns slot -> result dict (host scalars).

        ``_healing`` is the rebuild path's override: ``heal_bucket`` keeps
        the quarantine flag SET while it replays streams into the fresh
        slab (so admissions stay 503-refused for the whole rebuild) and
        dispatches through it with this flag.

        Phase wall times land in ``last_timing`` (build = host input prep,
        step = executable call through host sync) so the batcher can
        attribute its tick span mechanically — the queue-wait / dispatch /
        step breakdown the load generator reports."""
        import time as _time

        import jax
        import jax.numpy as jnp

        if _healing:
            if self.failed is not None:
                raise RuntimeError(
                    f"bucket {self.task}/{self.spec.method} is failed "
                    f"(restart to recover): {self.failed}")
        else:
            self._check_available()
        t0 = _time.perf_counter()
        self._apply_staged()  # admissions since the last slab access
        S = self.capacity
        q = self.acq_batch
        lane = (S,) if q == 1 else (S, q)
        pending = np.zeros(S, bool)
        do_update = np.zeros(S, bool)
        idx = np.zeros(lane, np.int32)
        label = np.zeros(lane, np.int32)
        prob = np.zeros(lane, np.float32)
        for slot, r in requests.items():
            pending[slot] = True
            do_update[slot] = bool(r.get("do_update", False))
            # q > 1 buckets carry q-wide label batches per request (the
            # batch-label verb); values arrive as length-q lists
            idx[slot] = r.get("idx", 0) if q == 1 else np.asarray(
                r.get("idx") if r.get("idx") is not None else [0] * q,
                np.int32)
            label[slot] = r.get("label", 0) if q == 1 else np.asarray(
                r.get("label") if r.get("label") is not None else [0] * q,
                np.int32)
            prob[slot] = r.get("prob", 0.0) if q == 1 else np.asarray(
                r.get("prob") if r.get("prob") is not None else [0.0] * q,
                np.float32)
        req = SlotRequest(
            pending=jnp.asarray(pending), do_update=jnp.asarray(do_update),
            idx=jnp.asarray(idx), label=jnp.asarray(label),
            prob=jnp.asarray(prob))
        t1 = _time.perf_counter()
        if self._step_exec is not None:
            self.warm_hits += 1
            step = self._step_exec
        else:
            # lazy-jit fallback: a bucket serving traffic before (or
            # without) warm() pays first-hit compilation here — counted so
            # /stats can show the warm pool actually covered the traffic
            self.warm_misses += 1
            step = self._step
        if self._faults is not None:
            self._faults.fire("step_pre", task=self.task)  # slow_step
        try:
            new_states, new_keys, out = step(self.states, self.keys, req)
            if self._faults is not None:
                # step_raise injects HERE: the executable has run, so with
                # donation the old carries are already consumed — exactly
                # the production failure the quarantine path recovers from
                self._faults.fire("step_post", task=self.task)
            self.states, self.keys = new_states, new_keys
        except BaseException as e:
            # with donation, a failed execution may have consumed the
            # carry buffers — the slab is then LOST, but not the sessions:
            # quarantine the bucket so recovery can rebuild the slab from
            # the sessions' recorder streams (serve/recovery.py); until it
            # does, dispatch/admission get an attributable error instead
            # of 'Array has been deleted'
            if self.donate and any(
                    getattr(x, "is_deleted", lambda: False)()
                    for x in jax.tree.leaves((self.states, self.keys))):
                self.quarantined = (
                    f"slab step failed after consuming donated carries: "
                    f"{e!r}")
            raise
        out = jax.tree.map(np.asarray, out)  # one host sync for the batch
        if self._faults is not None and "step_nan" in self._faults.fire(
                "step_out", task=self.task):
            # simulated numeric corruption: poison the outputs the digest
            # verification must catch (the silent-degradation probe)
            out = out._replace(
                next_prob=np.full_like(out.next_prob, np.nan),
                pbest_max=np.full_like(out.pbest_max, np.nan),
                pbest_entropy=np.full_like(out.pbest_entropy, np.nan))
        t2 = _time.perf_counter()
        self.last_timing = {"build_s": t1 - t0, "step_s": t2 - t1}
        has_digest = self._get_pbest is not None

        def _next(arr, slot):
            # q-wide buckets propose (q,) next points per round; the host
            # row carries them as plain lists (JSON/recorder-safe)
            if q == 1:
                return (int(arr[slot]) if arr.dtype.kind in "iu"
                        else float(arr[slot]))
            return [int(v) for v in arr[slot]] if arr.dtype.kind in "iu" \
                else [float(v) for v in arr[slot]]

        return {
            slot: {
                "next_idx": _next(out.next_idx, slot),
                "next_prob": _next(out.next_prob, slot),
                "best": int(out.best[slot]),
                "stochastic": bool(out.stochastic[slot]),
                "pbest_max": (float(out.pbest_max[slot]) if has_digest
                              else None),
                "pbest_entropy": (float(out.pbest_entropy[slot])
                                  if has_digest else None),
            }
            for slot in requests
        }

    # -- cheap per-session reads ------------------------------------------
    def slot_state(self, slot: int):
        import jax

        self._apply_staged()  # a pre-first-dispatch read must see its init
        return jax.tree.map(lambda x: x[slot], self.states)

    def surrogate_stats(self) -> Optional[dict]:
        """Aggregate surrogate-scorer evidence from the slab carry, or
        None when this bucket's selector runs the exact scorer.

        A ``--eig-scorer surrogate:k`` bucket's slab states carry the
        per-slot :class:`~coda_tpu.selectors.surrogate.SurrogateFit`
        counters (rounds / fallbacks / fit refolds / last gate margin);
        this sums them over LIVE slots under the dispatch lock — an
        on-demand /stats-time read of a few scalar words per slot, never
        a per-tick device sync."""
        fit = getattr(self.states, "surrogate", None)
        if fit is None:
            return None
        with self.lock:
            self._apply_staged()
            fit = self.states.surrogate
            with self._host_lock:
                free = set(self._free)
            live = np.asarray([s for s in range(self.capacity)
                               if s not in free], dtype=np.int64)
            rounds = np.asarray(fit.rounds)
            fallbacks = np.asarray(fit.fallbacks)
            fits = np.asarray(fit.fits)
            margins = np.asarray(fit.margin)
            prounds = np.asarray(getattr(fit, "prior_rounds", 0))
            prejects = np.asarray(getattr(fit, "prior_rejects", 0))
        if live.size == 0:
            return {"rounds": 0, "fallbacks": 0, "fit_refreshes": 0,
                    "contract_margin": None, "prior_rounds": 0,
                    "prior_rejects": 0}
        active = live[rounds[live] > 0]
        finite = (np.isfinite(margins[active])
                  if active.size else np.zeros(0, bool))
        margin = (float(np.min(margins[active][finite]))
                  if finite.any() else None)
        return {
            "rounds": int(rounds[live].sum()),
            "fallbacks": int(fallbacks[live].sum()),
            "fit_refreshes": int(fits[live].sum()),
            "contract_margin": margin,
            # the prior evidence pair, device-read from the same carry:
            # warmup rounds the pool credited to live sessions, and gate
            # rejections that fired INSIDE a credited warmup window
            "prior_rounds": (int(prounds[live].sum())
                             if prounds.ndim else 0),
            "prior_rejects": (int(prejects[live].sum())
                              if prejects.ndim else 0),
        }

    def pbest(self, slot: int):
        """P(model is best) for one slot, when the method exposes it (CODA's
        ``get_pbest`` extra) — the cheap posterior read behind GET /best."""
        if self._get_pbest is None:
            return None
        self._check_available()
        fn = self._pbest_exec if self._pbest_exec is not None \
            else self._get_pbest
        return np.asarray(fn(self.slot_state(slot)))

    def pbest_at(self, slot: int):
        """:meth:`pbest` without the per-leaf host indexing: ONE jitted
        call gathers the slot's state inside the executable and folds it
        straight into ``get_pbest``. Same values as :meth:`pbest`; this
        is the quality plane's per-tick read (``slot_state``'s
        ``tree.map`` of host-side index ops was measurable at serving
        rates). The slot index is a traced argument, so every slot
        shares one compile."""
        import jax

        if self._get_pbest is None:
            return None
        self._check_available()
        self._apply_staged()
        if self._pbest_at is None:
            gp = self._get_pbest

            def _at(states, s):
                return gp(jax.tree.map(lambda x: x[s], states))

            self._pbest_at = jax.jit(_at)
        return np.asarray(self._pbest_at(self.states, slot))

    # -- checkpoint / heal support (serve/recovery.py drives these) --------
    def _ensure_digest_fn(self):
        import jax
        import jax.numpy as jnp

        if self._digest_fn is None:
            from coda_tpu.ops.masked import entropy2

            get_pbest = self.selector.extras["get_pbest"]

            def _digest(state):
                pb = get_pbest(state).astype(jnp.float32)
                return pb.max(), entropy2(pb)

            self._digest_fn = jax.jit(_digest)
        return self._digest_fn

    def digest(self, slot: int):
        """(pbest_max, pbest_entropy) of one slot's CURRENT state, or None
        when the method exposes no posterior — the same two float32 words
        the slab step emits per round, read standalone so an imported
        snapshot verifies against its stream's last recorded digest
        without spending a dispatch. Caller holds ``lock``."""
        if self._get_pbest is None:
            return None
        m, e = self._ensure_digest_fn()(self.slot_state(slot))
        return float(np.asarray(m)), float(np.asarray(e))

    def digest_leaves(self, leaves):
        """The same posterior digest computed on IMPORTED host leaves,
        without touching the slab — no bucket lock, so the wake fast path
        (serve/tiering.py) never waits out an in-flight dispatch just to
        verify a payload. None when the method exposes no posterior."""
        if self._get_pbest is None:
            return None
        state = self._state_from_leaves(leaves)
        m, e = self._ensure_digest_fn()(state)
        return float(np.asarray(m)), float(np.asarray(e))

    def fit_from_leaves(self, leaves) -> Optional[dict]:
        """The surrogate fit's pool-contribution statistics ``{"A", "b",
        "n", "rounds"}`` extracted from HOST snapshot leaves (the
        sweeper's batched demotion materialized them already — the
        pool's demote-time contribution costs no extra device sync).
        None when this bucket's selector carries no fit."""
        if getattr(self.states, "surrogate", None) is None:
            return None
        if self._fit_leaf_idx is None:
            import jax

            ref, _ = self._fresh_slot_state(0)
            idx = {}
            flat = jax.tree_util.tree_flatten_with_path(ref)[0]
            for i, (path, _leaf) in enumerate(flat):
                names = [getattr(p, "name", None) for p in path]
                if "surrogate" in names:
                    idx[names[-1]] = i
            self._fit_leaf_idx = idx
        idx = self._fit_leaf_idx
        try:
            return {k: np.asarray(leaves[idx[k]])
                    for k in ("A", "b", "n", "rounds", "fits")}
        except (KeyError, IndexError):
            return None

    def slot_fit(self, slot: int) -> Optional[dict]:
        """One LIVE slot's fit contribution statistics ``{"A", "b", "n",
        "rounds", "fits"}`` as host arrays (the close-time pool
        contribution's read — a few hundred words, on demand)."""
        if getattr(self.states, "surrogate", None) is None:
            return None
        with self.lock:
            self._apply_staged()
            fit = self.states.surrogate
            return {"A": np.asarray(fit.A[slot]),
                    "b": np.asarray(fit.b[slot]),
                    "n": np.asarray(fit.n[slot]),
                    "rounds": np.asarray(fit.rounds[slot]),
                    "fits": np.asarray(fit.fits[slot])}

    def snapshot_slot(self, slot: int):
        """Host-materialized ``(state leaves, key)`` of one slot.

        Takes the dispatch lock and converts every leaf to numpy BEFORE
        returning: with donated buffers, the next slab step CONSUMES the
        arrays a lock-free reader would still be holding ('Array has been
        deleted' mid-export) — the export/donation race. The snapshot is
        therefore a stable host copy no later dispatch can invalidate."""
        import jax

        with self.lock:
            self._check_available()
            state = self.slot_state(slot)
            leaves = [np.asarray(x) for x in jax.tree.leaves(state)]
            key = np.asarray(self.keys[slot])
        return leaves, key

    def _state_from_leaves(self, leaves):
        """Validated state pytree from imported host leaves: the list is
        order/shape/dtype-checked against this bucket's own state
        structure — the structural half of the import fingerprint guard."""
        import jax
        import jax.numpy as jnp

        ref, _ = self._fresh_slot_state(0)
        ref_leaves, treedef = jax.tree.flatten(ref)
        if len(leaves) != len(ref_leaves):
            raise ValueError(
                f"snapshot carries {len(leaves)} leaves; this bucket's "
                f"state has {len(ref_leaves)}")
        cast = []
        for got, want in zip(leaves, ref_leaves):
            arr = np.asarray(got)
            if arr.shape != want.shape or arr.dtype != want.dtype:
                raise ValueError(
                    f"snapshot leaf {arr.dtype}{arr.shape} != bucket "
                    f"state leaf {want.dtype}{want.shape}")
            cast.append(jnp.asarray(arr))
        return jax.tree.unflatten(treedef, cast)

    def snapshot_slots(self, slots) -> dict:
        """Host-materialized ``(state leaves, key)`` for MANY slots under
        ONE lock acquisition: the whole slab transfers once per leaf and
        the per-slot rows are sliced on the host. The tier sweeper's
        batched demotion path (serve/tiering.py) — per-slot snapshots
        would serialize every demotion behind an in-flight dispatch, and
        paging out a 100k-session backlog needs hundreds of demotions per
        second, not one per tick gap. Returns ``{slot: (leaves, key)}``."""
        import jax

        slots = list(slots)
        if not slots:
            return {}
        with self.lock:
            self._check_available()
            self._apply_staged()
            host_leaves = [np.asarray(x)
                           for x in jax.tree.leaves(self.states)]
            host_keys = np.asarray(self.keys)
        return {
            slot: ([x[slot] for x in host_leaves], host_keys[slot])
            for slot in slots
        }

    def restore_slot(self, slot: int, leaves, key) -> None:
        """Overwrite a slot's carries with imported host leaves (staged
        like an admission write; the slot must already be allocated)."""
        import jax.numpy as jnp

        state = self._state_from_leaves(leaves)
        with self._host_lock:
            self._staged.append(
                (slot, state, jnp.asarray(np.asarray(key), jnp.uint32)))

    def stage_fresh(self, slot: int, seed: int,
                    prior: Optional[dict] = None) -> None:
        """Stage a freshly-initialized state for an ALLOCATED slot — the
        replay-restore entry point: replay starts from the reference init
        (overriding any previously staged snapshot write; staged rows
        apply in order, last write wins). ``prior`` re-applies the
        applied-prior record the session was ADMITTED under, so a
        prior-seeded session's replay reproduces its init bitwise."""
        state, key = self._fresh_slot_state(seed, prior=prior)
        with self._host_lock:
            self._staged.append((slot, state, key))

    def reset_slab(self) -> None:
        """Reallocate a fresh zero slab in place of one lost to a failed
        donated step — the heal path's first move (caller holds ``lock``
        and then replays every live slot's stream into the new slab)."""
        import jax.numpy as jnp

        dummy = jnp.zeros((self.capacity, 2), jnp.uint32)
        self.states = self._slab_init(dummy)
        self.keys = jnp.zeros((self.capacity, 2), jnp.uint32)


# ---------------------------------------------------------------------------
# session store
# ---------------------------------------------------------------------------

@dataclass
class Session:
    """Host-side record of one live interactive session."""

    sid: str
    task: str
    bucket: Bucket
    slot: int
    seed: int
    n_labeled: int = 0
    last: dict = field(default_factory=dict)  # most recent SlotResult row
    # idempotent-label bookkeeping: client-supplied request_id -> the
    # completed result row (bounded LRU), and -> the in-flight Ticket. A
    # retried label with a known request_id is answered from here instead
    # of re-applied to the posterior; restore/import repopulate ``recent``
    # from the recorder stream so retries survive a process death too.
    recent: dict = field(default_factory=dict)
    pending: dict = field(default_factory=dict)
    # asynchronous crowd answers (POST /session/{id}/answer): per-slot
    # parked answers of the CURRENT round — slot -> {label, request_id,
    # seq} — plus the arrival counter the reorder-depth metric reads.
    # When all acq_batch slots are filled the park drains through ONE
    # batch-label dispatch (slot order, a deterministic synthetic
    # request_id), so out-of-order delivery commits identically to
    # in-order. Mutates only under the store lock; park rows in the
    # recorder stream + the export payload's ``parked`` field carry the
    # state across crash restore and migration (0 lost answers).
    parked: dict = field(default_factory=dict)
    park_seq: int = 0
    # set while import/restore is mid-replay: the sid is already published
    # (the client's handle must resolve) but the posterior and the dedupe
    # cache are not rebuilt yet — label dispatches answer retryable 503
    # instead of 404-ing or double-applying (cleared when restore completes)
    restoring: bool = False
    # ownership epoch (serve/router.py): bumped by every migration /
    # peer-page, stamped into the export payload and the stream meta. A
    # routed verb carries the router's epoch; a copy whose epoch is OLDER
    # than the verb's is stale (the session moved away and this copy
    # survived a partition or crash) and refuses with StaleOwner.
    epoch: int = 0
    # tiering bookkeeping (serve/tiering.py): ``pins`` counts in-flight
    # verbs/tickets holding the session resident — demotion requires the
    # count to be exactly its own pin, so it cleanly loses every race
    # against live traffic; ``last_used`` is the LRU axis idle-driven and
    # watermark demotion order on. Both mutate only under the store lock.
    pins: int = 0
    last_used: float = field(default_factory=time.monotonic)
    # the applied-prior record this session's fit was SEEDED from at
    # admission ({"digest", "credit", <prior_to_dict fields>}; None =
    # cold init). Rides the recorder stream meta and the export payload
    # so every replay-based restore (import fallback, crash restore,
    # heal, offline verify) re-applies the exact same prior — the pool
    # may have evolved since, but this session's history has not.
    prior_fit: Optional[dict] = None
    # whether this session's fit statistics were already folded into the
    # cross-session pool (contribute exactly once: close OR demote)
    prior_contributed: bool = False


def _round_up(n: int, quantum: int) -> int:
    return ((n + quantum - 1) // quantum) * quantum


class SessionStore:
    """Multiplexes sessions onto per-(task, spec, shape) slabs.

    ``capacity`` bounds EACH bucket's slab (admission past it raises
    :class:`SlabFull` — the server's 503). ``bucket_n`` is the N-padding
    quantum (see module docstring; 1 = exact shapes).
    Thread safety, three tiers so one bucket's work never stalls another's:
    the store lock guards only the host dicts (tasks/buckets/sessions —
    microseconds); each BUCKET's dispatch lock serializes slab ACCESS only
    (the batcher's step, posterior reads) while admission/close never take
    it — they stage their slot writes under the bucket's cheap host mutex
    for the next lock holder to apply, so a burst of opens never convoys
    behind an in-flight slab step; and bucket CONSTRUCTION (selector
    statics + init compile, potentially seconds) runs under a dedicated
    build lock with no other lock held, so standing traffic keeps flowing
    while a new (task, spec) warms up.
    """

    def __init__(self, capacity: int = 64, bucket_n: int = 1,
                 step_impl: Optional[str] = None, donate: bool = True,
                 faults=None, registry=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if bucket_n < 1:
            raise ValueError("bucket_n must be >= 1")
        self.capacity = capacity
        self.bucket_n = bucket_n
        self.step_impl = step_impl
        self.donate = donate
        self.faults = faults                 # shared FaultInjector or None
        self.registry = registry             # cost-gauge registry (or None
        #                                      = process-global); ServeApp
        #                                      sets its telemetry's here
        self.prior_resolver = None           # bucket -> PriorStats|None;
        #                                      ServeApp installs the pool
        #                                      lookup so lazily-built
        #                                      buckets seed immediately
        self._tasks: dict[str, Any] = {}     # name -> (H, N, C) ndarray
        self._meta: dict[str, dict] = {}     # name -> class/model names
        self._buckets: dict[tuple, Bucket] = {}
        self._sessions: dict[str, Session] = {}
        self.lock = threading.RLock()
        self._build_lock = threading.Lock()

    # -- tasks -------------------------------------------------------------
    def register_task(self, name: str, preds, class_names=None,
                      model_names=None) -> None:
        preds = np.asarray(preds, np.float32)
        if preds.ndim != 3:
            raise ValueError(f"preds must be (H, N, C), got {preds.shape}")
        from coda_tpu.telemetry.recorder import dataset_digest

        with self.lock:
            self._tasks[name] = preds
            H, N, C = preds.shape
            self._meta[name] = {
                "class_names": list(class_names
                                    or [f"class {c}" for c in range(C)]),
                "model_names": list(model_names
                                    or [f"model {h}" for h in range(H)]),
                # once per task, not per session: the digest rides every
                # session's record-stream meta so export/import and the
                # offline stream verifier can refuse to replay a session
                # against different data
                "shape": [H, N, C],
                "digest": dataset_digest(preds),
            }

    def tasks(self) -> list[str]:
        with self.lock:
            return sorted(self._tasks)

    def task_meta(self, name: str) -> dict:
        with self.lock:
            return dict(self._meta[name])

    def task_preds(self, name: str):
        """The task's registered (H, N, C) prediction tensor, or None —
        the quality plane's consensus-pi_hat read (the array is written
        once at registration and never mutated, so callers may read it
        without holding the store lock afterwards)."""
        with self.lock:
            return self._tasks.get(name)

    def has_fast_admission(self, task: str, spec: SelectorSpec) -> bool:
        """Whether admission for this (task, spec) is pure sub-ms host
        work: the bucket exists AND warm() cached its key-independent init
        state (the front door's inline-fast-path test). A missing bucket
        means seconds of selector statics + compiles; a cold one still
        runs a full init computation per admission — neither belongs on
        an event loop."""
        with self.lock:
            preds = self._tasks.get(task)
            if preds is None:
                return False
            H, N, C = preds.shape
            key = (task, spec, (H, _round_up(N, self.bucket_n), C))
            b = self._buckets.get(key)
        if b is None or b._init_state is None:
            return False
        # a full slab disqualifies the inline path too: admission would
        # then demote the coldest session (snapshot work — serve/tiering),
        # which must never run on the event loop
        with b._host_lock:
            return len(b._free) > 0

    def _bucket_for(self, task: str, spec: SelectorSpec) -> Bucket:
        with self.lock:
            preds = self._tasks[task]
        H, N, C = preds.shape
        n_pad = _round_up(N, self.bucket_n)
        key = (task, spec, (H, n_pad, C))
        with self.lock:
            b = self._buckets.get(key)
        if b is not None:
            return b
        # the expensive part (selector statics, init compile) runs with no
        # store/bucket lock held, so live traffic is untouched; the build
        # lock just keeps two threads from compiling the same bucket twice
        with self._build_lock:
            with self.lock:
                b = self._buckets.get(key)
            if b is not None:
                return b
            if n_pad != N:
                preds = np.pad(preds, ((0, 0), (0, n_pad - N), (0, 0)))
            b = Bucket(preds, spec, self.capacity, n_valid=N, task=task,
                       step_impl=self.step_impl, donate=self.donate,
                       faults=self.faults, registry=self.registry)
            if self.prior_resolver is not None:
                # buckets build lazily at first admission — a pool loaded
                # before that (restart restore) must still seed it
                try:
                    b.set_prior(self.prior_resolver(b))
                except Exception:
                    pass  # the pool never blocks a bucket build
            with self.lock:
                self._buckets[key] = b
            return b

    # -- sessions ----------------------------------------------------------
    def open(self, task: str, spec: SelectorSpec, seed: int = 0,
             sid: Optional[str] = None, restoring: bool = False,
             prior="pool") -> Session:
        """Admit a session. ``sid`` pins the session id — the
        import/restore path, where the client already holds its handle
        from the exporting server and must keep it across the migration.
        ``restoring`` publishes the session already gated (see
        :class:`Session`) so no label can slip in before the flag is set.

        ``prior``: ``"pool"`` (default) seeds a NEW session's surrogate
        fit from the bucket's current pool prior (a no-op until
        ``Bucket.set_prior`` installed one); an explicit applied-prior
        record re-applies exactly that one (the restore paths); None
        forces a cold init."""
        with self.lock:
            if task not in self._tasks:
                raise KeyError(f"unknown task {task!r}; registered: "
                               f"{self.tasks()}")
            if sid is not None and sid in self._sessions:
                raise ValueError(f"session id {sid!r} already live here")
        bucket = self._bucket_for(task, spec)
        # resolve the prior ONCE so the allocate-time seeding and the
        # session's recorded prior_fit can never disagree (the pool may
        # swap the bucket prior concurrently)
        applied = bucket.prior if prior == "pool" else prior
        # no bucket (dispatch) lock: allocate stages its slab write, so
        # admission never waits out an in-flight slab step
        slot = bucket.allocate(seed, prior=applied)  # raises SlabFull
        sess = Session(sid=sid or secrets.token_hex(8), task=task,
                       bucket=bucket, slot=slot, seed=seed,
                       restoring=restoring, prior_fit=applied)
        with self.lock:
            if sess.sid in self._sessions:  # lost an import race
                bucket.release(slot)
                raise ValueError(f"session id {sess.sid!r} already live "
                                 "here")
            self._sessions[sess.sid] = sess
        return sess

    def get(self, sid: str) -> Session:
        with self.lock:
            sess = self._sessions.get(sid)
            if sess is None:
                raise UnknownSession(sid)
            return sess

    # -- pinning (the tiering race protocol; see serve/tiering.py) ---------
    def get_pinned(self, sid: str) -> Session:
        """Atomic lookup + pin: the session cannot be demoted off-slab
        while the pin is held. Callers unpin on every exit path (a label
        verb hands its pin to the ticket, which unpins on resolution)."""
        with self.lock:
            sess = self._sessions.get(sid)
            if sess is None:
                raise UnknownSession(sid)
            sess.pins += 1
            sess.last_used = time.monotonic()
            return sess

    def pin(self, sess: Session) -> None:
        with self.lock:
            sess.pins += 1

    def unpin(self, sess: Session) -> None:
        with self.lock:
            sess.pins = max(0, sess.pins - 1)

    def slab_occupancy(self) -> int:
        """Live slab slots across buckets — distinct from open sessions
        the moment a session can live off-slab (warm/cold tiers)."""
        return sum(b.live for b in self.buckets())

    def alive(self, sid: str) -> bool:
        with self.lock:
            return sid in self._sessions

    def close(self, sid: str) -> None:
        with self.lock:
            sess = self._sessions.pop(sid, None)
        if sess is None:
            raise UnknownSession(sid)
        sess.bucket.release(sess.slot)

    def live_sessions(self) -> int:
        with self.lock:
            return len(self._sessions)

    def buckets(self) -> list[Bucket]:
        with self.lock:
            return list(self._buckets.values())

    def sessions_on(self, bucket: Bucket) -> list[Session]:
        """The live sessions riding one bucket's slab (the heal path's
        worklist — every one of them must be rebuilt and verified)."""
        with self.lock:
            return [s for s in self._sessions.values()
                    if s.bucket is bucket]
