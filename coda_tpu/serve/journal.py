"""Migration journal: a per-router append-log that makes session moves
crash-consistent.

A migration is a multi-step protocol (quiesce+hold on the source, export,
import on the destination, fence the source) and the router — or either
replica — can be SIGKILLed between any two steps. Without a durable
record, a crash mid-move leaves the session's ownership in doubt: did the
import land? is the source copy still authoritative? This log resolves
that: every phase transition is one appended JSON line, flushed before
the next step runs, so a restarted router replays the log and knows
exactly how far each move got.

Framing is the same torn-tail-tolerant contract as the recorder streams
and ``serve/spill.py``: one JSON object per line, append + flush per
record; a process killed mid-write leaves at most one truncated FINAL
line, which the load path drops. A torn line anywhere else is real
corruption and raises.

Record shape (every record carries the migration id ``mid`` — unique per
move — so interleaved moves of different sessions never alias)::

    {"mid": "<sid>#<seq>", "phase": "intent",   "sid", "src", "dst",
     "epoch"}
    {"mid": ...,           "phase": "exported",  "digest", "n_labeled"}
    {"mid": ...,           "phase": "imported"}
    {"mid": ...,           "phase": "committed", "fenced": true|false}
    {"mid": ...,           "phase": "aborted",   "reason": "..."}

Resolution on restart (:meth:`MigrationJournal.in_doubt` feeds the
router's ``recover_from_journal``): a move whose last phase is ``intent``
or ``exported`` may or may not have imported — probe the destination; one
at ``imported`` definitely committed on the target — finalize by fencing
the source. Either way the outcome is *didn't move* or *moved exactly
once*, never gone and never doubled.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

#: phases that end a migration (anything else is in-doubt after a crash)
TERMINAL_PHASES = ("committed", "aborted")


def payload_digest(payload: dict) -> str:
    """A cheap identity digest of an export payload: enough to recognise
    "the copy the journal saw" on the destination during recovery (sid +
    epoch + committed-label count + the stream's last posterior digest),
    without hashing megabytes of carries."""
    rows = payload.get("rows") or []
    last = rows[-1] if rows else {}
    key = {
        "sid": payload.get("session"),
        "epoch": int(payload.get("epoch") or 0),
        "n_labeled": int(payload.get("n_labeled") or 0),
        "rounds": len(rows),
        "pbest_max": last.get("pbest_max"),
        "pbest_entropy": last.get("pbest_entropy"),
    }
    return hashlib.sha256(
        json.dumps(key, sort_keys=True).encode()).hexdigest()[:16]


class MigrationJournal:
    """Append-only migration log + the in-memory state it rebuilds.

    Thread-safe (one lock around the fd and the state maps). The journal
    is an *ordering* log, not a database: the load path folds records per
    ``mid`` (last phase wins) and per ``sid`` (the latest committed epoch
    wins) — that fold is the router's durable epoch/placement map.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._seq = 0
        # mid -> folded record (intent fields + latest phase + extras)
        self._moves: dict[str, dict] = {}
        self.records_loaded = 0
        self.torn_tail_dropped = False
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._load()
        self._fd = open(path, "a")

    # -- load (torn-tail-tolerant, same contract as the recorder) ----------
    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    self.torn_tail_dropped = True
                    break  # the crash the flush-per-record contract allows
                raise
            self._fold(rec)
            self.records_loaded += 1
        for mid in self._moves:
            try:
                self._seq = max(self._seq, int(mid.rsplit("#", 1)[1]) + 1)
            except (IndexError, ValueError):
                pass

    def _fold(self, rec: dict) -> None:
        mid = rec.get("mid")
        if not mid:
            return
        cur = self._moves.setdefault(mid, {})
        cur.update({k: v for k, v in rec.items() if v is not None})

    # -- append ------------------------------------------------------------
    def _append(self, rec: dict) -> None:
        with self._lock:
            self._fold(rec)
            try:
                self._fd.write(json.dumps(rec, separators=(",", ":"))
                               + "\n")
                self._fd.flush()
            except OSError:
                # a full disk must not fail the migration itself — the
                # epoch fence still protects correctness; only crash
                # recovery loses this move's record
                pass

    def begin(self, sid: str, src: str, dst: str, epoch: int) -> str:
        with self._lock:
            mid = f"{sid}#{self._seq}"
            self._seq += 1
        self._append({"mid": mid, "phase": "intent", "sid": sid,
                      "src": src, "dst": dst, "epoch": int(epoch)})
        return mid

    def record(self, mid: str, phase: str, **extra) -> None:
        rec = {"mid": mid, "phase": phase}
        rec.update(extra)
        self._append(rec)

    # -- reads -------------------------------------------------------------
    def in_doubt(self) -> list[dict]:
        """Folded records of every move whose last phase is not terminal
        — the set a restarted router must resolve before serving."""
        with self._lock:
            return [dict(m) for m in self._moves.values()
                    if m.get("phase") not in TERMINAL_PHASES]

    def committed(self) -> dict:
        """``sid -> {epoch, dst}`` from committed records (highest epoch
        per sid wins) — the durable half of the router's epoch/placement
        map."""
        out: dict[str, dict] = {}
        with self._lock:
            moves = list(self._moves.values())
        for m in moves:
            if m.get("phase") != "committed":
                continue
            sid = m.get("sid")
            ep = int(m.get("epoch") or 0)
            if sid and ep >= out.get(sid, {}).get("epoch", -1):
                out[sid] = {"epoch": ep, "dst": m.get("dst")}
        return out

    def stats(self) -> dict:
        with self._lock:
            phases: dict[str, int] = {}
            for m in self._moves.values():
                p = m.get("phase") or "?"
                phases[p] = phases.get(p, 0) + 1
            return {"path": self.path, "moves": len(self._moves),
                    "records_loaded": self.records_loaded,
                    "torn_tail_dropped": self.torn_tail_dropped,
                    "phases": phases}

    def close(self) -> None:
        with self._lock:
            try:
                self._fd.close()
            except OSError:
                pass
