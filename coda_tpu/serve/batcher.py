"""Continuous-batching dispatcher: many sessions' requests, one launch.

The host half of the serving layer. Front-door workers (asyncio handlers or
in-process callers) submit one request per user action (session start,
oracle label) and wait on a ticket; a single batcher thread drains the
queue, forms a batch, groups by bucket, and executes ONE compiled masked
slab step per bucket (:func:`coda_tpu.serve.state.make_slab_step`).
Accelerator dispatch cost is thus amortized over every concurrent session
instead of paid per click.

Batch formation is **continuous**: a completed tick immediately starts
forming the next one from whatever queued while it ran — no fixed wait
gates a ready batch, and tickets arriving while a batch forms join it up
to ``max_batch``. Formation then lingers only while arrivals keep
flowing: each arrival refreshes a ``max_wait`` quiet-gap budget, so the
cohort the previous tick just answered can resubmit as a burst and ride
this tick instead of the next (the masked slab step costs the same at
any occupancy, so a few ms of pickup buys half the slab a whole tick of
latency), while a single idle request is dispatched ``max_wait`` after
it arrives. Total formation time is hard-capped by ``max_linger`` so
steady trickle arrival can never stretch a tick's formation window
indefinitely — the cap, not the gap, is the worst-case bound.

Two requests for the same slot never ride one tick (the second would read
the first's pre-update state); the collision is requeued for the next tick.
Closed-loop clients can't produce collisions (they wait for their reply),
so this path only guards misbehaving open-loop callers.

Tickets resolve exactly once (a lock arbitrates dispatch completion against
wait-timeout cancellation — the loser of the race is a no-op), and a
resolution wakes both the blocking ``wait()`` path and any asyncio waiter
registered by ``wait_async()`` (the front door's bridge from the batcher
thread into the event loop).
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Ticket:
    """One submitted request and its rendezvous.

    Resolution (result, error, or cancellation) happens EXACTLY once: the
    first of {dispatch completion, dispatch failure, cancel} wins under
    ``_lock`` and fires ``done`` plus any registered asyncio futures; later
    attempts return False and change nothing. This is what makes a
    wait-timeout racing an in-flight dispatch safe — the ticket is never
    double-completed, whichever side wins.
    """

    session: object                 # state.Session
    do_update: bool
    idx: int = 0
    label: int = 0
    prob: float = 0.0
    request_id: Optional[str] = None  # client idempotency token (labels)
    # trace context (telemetry.trace.TraceContext) of the request this
    # ticket carries, or None when untraced. Read ONLY by spans/metrics/
    # recorder rows — never by dispatch math (the non-perturbation
    # contract: tracing off and on take bitwise-identical trajectories).
    trace: Optional[object] = None
    submitted: float = field(default_factory=time.perf_counter)
    collected: float = 0.0          # when the batcher picked it into a batch
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[dict] = None
    error: Optional[BaseException] = None
    cancelled: bool = False
    # resolution hook: runs EXACTLY once, whoever resolves the ticket
    # (complete/fail/cancel), outside the ticket lock. The serving layer
    # hands a session's demotion pin to its ticket through this — the pin
    # is released the instant the ticket is resolved, never twice.
    on_resolve: Optional[object] = None
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _async_waiters: list = field(default_factory=list)  # (loop, future)

    # -- resolution (exactly once) ----------------------------------------
    def _resolve_locked(self):
        """Caller holds ``_lock`` and has set result/error: mark done and
        hand back (waiters, hook) for POST-lock delivery — the hook takes
        other locks (the session store's, for the demotion pin), so it
        must never run inside the ticket lock."""
        self.done.set()
        waiters, self._async_waiters = self._async_waiters, []
        cb, self.on_resolve = self.on_resolve, None
        return waiters, cb

    @staticmethod
    def _run_hook(cb) -> None:
        if cb is not None:
            try:
                cb()
            except Exception:
                pass  # a hook failure must never mask the resolution

    def _deliver(self, waiters) -> None:
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(self._resolve_future, fut)
            except RuntimeError:
                pass  # loop already closed; the waiter is gone anyway

    def _resolve_future(self, fut) -> None:
        if fut.done():
            return
        if self.error is not None:
            fut.set_exception(self.error)
        else:
            fut.set_result(self.result)

    def complete(self, result: dict, collector: Optional[dict] = None
                 ) -> bool:
        """Resolve with a result. With a ``collector`` ({loop: [(ticket,
        future), ...]}), async waiters are appended there instead of each
        paying its own ``call_soon_threadsafe`` — the dispatcher flushes
        one cross-thread wakeup per event loop per tick instead of one per
        ticket (256 tickets = 256 loop wakeups otherwise, a measurable
        slice of the tick cycle on a busy host)."""
        with self._lock:
            if self.done.is_set():
                return False
            self.result = result
            waiters, cb = self._resolve_locked()
        self._run_hook(cb)
        if collector is None:
            self._deliver(waiters)
        else:
            for loop, fut in waiters:
                collector.setdefault(loop, []).append((self, fut))
        return True

    def fail(self, error: BaseException) -> bool:
        with self._lock:
            if self.done.is_set():
                return False
            self.error = error
            waiters, cb = self._resolve_locked()
        self._run_hook(cb)
        self._deliver(waiters)
        return True

    def cancel(self, reason: str = "timeout") -> bool:
        """Mark the ticket dead-on-arrival for the dispatcher. Wins only if
        nothing resolved it yet (a dispatch that already completed it keeps
        its result — the caller lost the race and gets the real answer)."""
        with self._lock:
            if self.done.is_set():
                return False
            self.cancelled = True
            self.error = RuntimeError(f"request cancelled ({reason})")
            waiters, cb = self._resolve_locked()
        self._run_hook(cb)
        self._deliver(waiters)
        return True

    # -- waiting -----------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> dict:
        """Block for the result. On timeout the ticket is CANCELLED before
        raising: a still-queued request must not fire later against a slot
        the caller has given up on (it could have been freed and reassigned
        — the dispatch would advance another session's PRNG stream — or,
        for a label the client will retry, apply the same update twice).
        Best-effort: a ticket already inside a dispatch completes, and if
        the dispatch resolves the ticket before the cancel lands, the real
        result is returned instead of raising."""
        if not self.done.wait(timeout):
            if self.cancel("serve dispatch timed out"):
                raise TimeoutError("serve dispatch timed out")
            # lost the race: a dispatch completed us during the timeout
        if self.error is not None:
            raise self.error
        return self.result

    async def wait_async(self, timeout: Optional[float] = None) -> dict:
        """Awaitable twin of :meth:`wait` for the asyncio front door: the
        batcher thread resolves the future via ``call_soon_threadsafe``, so
        the event loop never blocks on accelerator work."""
        import asyncio

        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        with self._lock:
            if self.done.is_set():
                self._resolve_future(fut)
            else:
                self._async_waiters.append((loop, fut))
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            if self.cancel("serve dispatch timed out"):
                raise TimeoutError("serve dispatch timed out") from None
            if self.error is not None:
                raise self.error
            return self.result


def _deliver_batch(items: list) -> None:
    """Resolve many tickets' futures inside their event loop (one
    ``call_soon_threadsafe`` delivered this whole list)."""
    for t, fut in items:
        t._resolve_future(fut)


class Batcher:
    """The dispatcher thread around a :class:`SessionStore`.

    ``max_batch`` caps requests per tick. ``max_wait`` is the quiet-gap
    budget: a tick dispatches once no new ticket has arrived for
    ``max_wait`` (a full batch never waits at all — continuous batching
    admits everything already queued immediately). ``max_linger`` bounds
    TOTAL formation time of any tick regardless of arrival pattern
    (default ``4x max_wait``); pause time is excluded, since a paused
    batcher deliberately holds its batch (the lockstep hook).
    ``start()``/``stop()`` manage the thread; ``pause()``/``resume()``
    freeze ticking with the queue still accepting — the
    deterministic-occupancy hook the lockstep load generator and the
    batching tests use.
    """

    def __init__(self, store, metrics=None, max_batch: int = 256,
                 max_wait: float = 0.002, max_linger: Optional[float] = None,
                 telemetry=None, recorder=None, faults=None, quality=None):
        self.store = store
        self.metrics = metrics
        # optional FaultInjector: tick-boundary crash points (the batcher
        # is where "the process died between ticks" is a meaningful,
        # deterministic place to die)
        self.faults = faults
        # recovery hook: called as on_bucket_failure(bucket, error) when a
        # dispatch leaves a bucket quarantined — the ServeApp wires this
        # to BucketHealer.schedule so the slab rebuild starts immediately,
        # off this thread
        self.on_bucket_failure = None
        # optional Telemetry: each per-bucket dispatch becomes a span on the
        # "host:batcher" lane (annotated so a live jax.profiler capture
        # shows the same tick names next to the device rows), with the
        # slab-step execution as a nested "step/<task>" span for the
        # queue-wait / dispatch / step attribution
        self.telemetry = telemetry
        # optional SessionRecorder: every completed ticket appends one
        # decision row to its session's record stream (the flight
        # recorder's serving face — GET /session/{id}/trace)
        self.recorder = recorder
        # optional QualityPlane (telemetry/quality.py): labeled tickets get
        # a pre-dispatch consensus-posterior read (calibration evidence +
        # the rows' additive-optional pred_label_prob field). Read-only —
        # quality on/off takes bitwise-identical decision trajectories,
        # same contract as tracing.
        self.quality = quality
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.max_linger = (4.0 * self.max_wait if max_linger is None
                           else float(max_linger))
        self.queue: queue.Queue = queue.Queue()
        self._running = False
        self._paused = threading.Event()
        self._paused.set()  # set = not paused
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Batcher":
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop ticking; with ``drain`` (default) finish queued work first."""
        if self._thread is None:
            return
        if drain:
            deadline = time.perf_counter() + timeout
            while not self.queue.empty() and time.perf_counter() < deadline:
                time.sleep(0.005)
        self._running = False
        self._paused.set()
        self._thread.join(timeout=timeout)
        self._thread = None
        # fail any tickets stranded by a non-drained stop
        self._flush_queue(RuntimeError("server stopped"))

    def _flush_queue(self, error: BaseException) -> None:
        """Fail everything currently queued (exactly-once resolution makes
        racing a live dispatch safe — whichever side resolves first wins)."""
        while True:
            try:
                t = self.queue.get_nowait()
            except queue.Empty:
                break
            self._forget_pending(t)
            t.fail(error)

    def pause(self) -> None:
        self._paused.clear()

    def resume(self) -> None:
        self._paused.set()

    # -- submission (front-door workers) -----------------------------------
    def submit(self, ticket: Ticket) -> Ticket:
        if not self._running:
            # fail fast with a retryable error instead of blackholing the
            # ticket until the request timeout: during a rolling restart
            # the client's retry/backoff loop needs to see the drain NOW
            # so it can land on the restored server
            self._forget_pending(ticket)
            ticket.fail(RuntimeError("server draining: batcher stopped"))
            return ticket
        self.queue.put(ticket)
        if not self._running:
            # raced a concurrent stop(): its final flush may have run
            # before our put landed, which would strand the ticket until
            # the request timeout — flush again (failing an already-
            # resolved ticket is a no-op)
            self._flush_queue(RuntimeError("server draining: batcher "
                                           "stopped"))
        return ticket

    def submit_start(self, session, trace=None) -> Ticket:
        return self.submit(Ticket(session=session, do_update=False,
                                  trace=trace))

    def submit_label(self, session, idx: int, label: int, prob: float,
                     request_id: Optional[str] = None,
                     trace=None) -> Ticket:
        return self.submit(Ticket(session=session, do_update=True, idx=idx,
                                  label=label, prob=prob,
                                  request_id=request_id, trace=trace))

    # -- the tick ----------------------------------------------------------
    def _collect(self) -> list:
        """Form one batch: block briefly for the first ticket, drain what's
        already queued, then linger while arrivals keep flowing.

        Each arrival refreshes a ``max_wait`` quiet-gap budget, so the
        window ends ``max_wait`` after the LAST arrival — but the total
        unpaused formation time is hard-capped at ``max_linger``, so
        steady trickle arrival bounds a tick's formation by time, not
        only by ``max_batch``.

        A pause() landing mid-collect (the thread may already hold tickets
        from its blocking get) HOLDS the batch — full or partial — until
        resume, and admits everything submitted during the pause (up to
        ``max_batch``) into this one dispatch; without the hold,
        lockstep's one-dispatch-per-round guarantee would be a race
        against the first submitter."""
        try:
            first = self.queue.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        # continuous-batching fast path: everything that queued while the
        # previous tick ran joins this one with zero added wait
        while len(batch) < self.max_batch:
            try:
                batch.append(self.queue.get_nowait())
            except queue.Empty:
                break
        # adaptive pickup linger: while arrivals keep flowing (gaps under
        # max_wait), keep collecting — the cohort the previous tick just
        # answered resubmits as a burst, and riding THIS tick instead of
        # the next saves it a whole slab step (which costs the same at any
        # occupancy). Each arrival refreshes the max_wait gap budget, so
        # the window ends max_wait after the LAST arrival, not the first;
        # the total unpaused formation time is hard-capped at max_linger
        # so steady trickle arrival bounds a tick's formation by time, not
        # only by max_batch.
        spent = 0.0  # unpaused linger seconds consumed (the cap's measure)
        while len(batch) < self.max_batch and spent < self.max_linger:
            if not self._paused.is_set():
                break  # pause-hold below owns the batch from here
            gap = min(self.max_wait, self.max_linger - spent)
            if gap <= 0:
                break
            t0 = time.perf_counter()
            try:
                batch.append(self.queue.get(timeout=gap))
            except queue.Empty:
                break  # arrivals went quiet for a full max_wait
            finally:
                spent += time.perf_counter() - t0
        # pause-hold: NEVER hand a batch (even a full one) to dispatch
        # while paused — wait out the pause and admit everything submitted
        # during it, so a lockstep round rides exactly one dispatch
        while not self._paused.is_set():
            self._paused.wait()
            while len(batch) < self.max_batch:
                try:
                    batch.append(self.queue.get_nowait())
                except queue.Empty:
                    break
        return batch

    def _loop(self) -> None:
        while self._running:
            self._paused.wait()
            batch = self._collect()
            if not batch:
                continue
            self._dispatch(batch)

    @staticmethod
    def _forget_pending(t: Ticket) -> None:
        """Drop a failed/dropped ticket's idempotency registration so the
        client's retry resubmits instead of re-joining a dead ticket.

        Identity-guarded: a cancelled ticket collected LATE must not
        erase the registration of the newer live ticket its client's
        retry already re-registered under the same request_id — that
        would reopen the double-apply window."""
        if t.request_id is not None:
            pending = t.session.pending
            if pending.get(t.request_id) is t:
                pending.pop(t.request_id, None)

    def _dispatch(self, batch: list) -> None:
        # group by bucket; at most one ticket per slot per tick. Cancelled
        # tickets (wait-timeout) and tickets whose session closed while
        # queued are dropped HERE, not dispatched — their slot may already
        # belong to someone else (see Ticket.wait). Their slot entry is
        # never marked pending, so the next tick sees a clean slab.
        if self.faults is not None:
            self.faults.fire("tick_pre")    # crash_before_tick
        now = time.perf_counter()
        per_bucket: dict = {}
        requeue: list = []
        for t in batch:
            t.collected = now
            if t.cancelled or not self.store.alive(t.session.sid):
                self._forget_pending(t)
                t.fail(RuntimeError("request cancelled (timeout or "
                                    "session closed while queued)"))
                continue
            if t.request_id is not None:
                done = t.session.recent.get(t.request_id)
                if done is not None:
                    # an earlier ticket for this request_id already
                    # committed its result — possible when the client's
                    # wait-timeout cancel lost the race to that ticket's
                    # in-flight dispatch and the retry resubmitted before
                    # the commit landed. Answer from the committed result;
                    # dispatching would apply the oracle answer twice.
                    self._forget_pending(t)
                    t.complete(dict(done))
                    continue
            slots = per_bucket.setdefault(t.session.bucket, {})
            if t.session.slot in slots:
                requeue.append(t)  # same-slot collision -> next tick
            else:
                slots[t.session.slot] = t
        depth = self.queue.qsize() + len(requeue)
        for bucket, slots in per_bucket.items():
            if bucket.quarantined is not None or bucket.failed is not None:
                # fail fast WITHOUT the bucket lock: the healer holds it
                # for the entire slab rebuild, and blocking here would
                # stall this thread — and with it every OTHER bucket's
                # dispatches — behind one bucket's recovery. The heal was
                # scheduled when the quarantine was set; waiters just need
                # the retryable error now.
                try:
                    bucket._check_available()
                except BaseException as e:
                    for t in slots.values():
                        self._forget_pending(t)
                        t.fail(e)
                    if bucket.quarantined is not None and \
                            self.on_bucket_failure is not None:
                        # a quarantine set OUTSIDE this thread's dispatch
                        # path (an import/restore replay dispatch failed)
                        # has no heal scheduled yet — kick it here;
                        # schedule() is a no-op while one is in flight
                        self.on_bucket_failure(bucket, e)
                    continue
            reqs = {
                slot: {"do_update": t.do_update, "idx": t.idx,
                       "label": t.label, "prob": t.prob}
                for slot, t in slots.items()
            }
            # OTel-style span links: one coalesced tick serves many
            # requests, so the tick span links to every member TRACE
            # (fan-in) instead of parenting to any single one — the span
            # recorder files it under each linked trace's retention ring
            links = sorted({t.trace.trace_id for t in slots.values()
                            if t.trace is not None})
            span_attrs = {"requests": len(slots), "depth": depth}
            if links:
                span_attrs["links"] = links
            span = (self.telemetry.span(
                        f"tick/{bucket.task}", lane="host:batcher",
                        annotate=True, **span_attrs)
                    if self.telemetry is not None
                    else contextlib.nullcontext())
            t0 = time.perf_counter()
            try:
                # the bucket lock serializes the slab swap against THIS
                # bucket's admission writes only — other buckets' dispatches
                # and admissions proceed (see SessionStore docstring)
                with span, bucket.lock:
                    pred_probs = {}
                    if self.quality is not None:
                        # pre-dispatch read of the exact posterior the
                        # round's decision is about to be made under: the
                        # consensus pi_hat's mass on each realized label
                        pred_probs = self.quality.pre_dispatch(
                            bucket, bucket.task,
                            [(slot, t.idx, t.label)
                             for slot, t in slots.items() if t.do_update])
                    results = bucket.dispatch(reqs)
            except BaseException as e:  # surface to every waiter, keep going
                for t in slots.values():
                    self._forget_pending(t)
                    t.fail(e)
                if bucket.quarantined is not None and \
                        self.on_bucket_failure is not None:
                    # the slab was lost to this failure: kick off the
                    # rebuild-from-streams heal (off this thread) so the
                    # waiters' retries find a healed bucket, not a corpse
                    self.on_bucket_failure(bucket, e)
                continue
            dt = time.perf_counter() - t0
            deliveries: dict = {}  # loop -> [(ticket, future), ...]
            timing = dict(bucket.last_timing)
            if self.telemetry is not None and timing.get("step_s"):
                # the slab-step execution as its own span, nested inside
                # the tick: tick minus step is host-side build/fan-out
                t_end = time.perf_counter()
                s0 = t_end - timing["step_s"]
                step_attrs = {"requests": len(slots),
                              "source": "aot" if bucket.is_warm else "jit"}
                if links:
                    step_attrs["links"] = links
                self.telemetry.spans.record(
                    f"step/{bucket.task}", lane="host:batcher",
                    t_start=s0, t_end=t_end, attrs=step_attrs)
            now = time.perf_counter()
            for slot, t in slots.items():
                r = results[slot]
                t.session.last = r
                t.session.last_used = time.monotonic()  # the tiers' LRU axis
                if t.do_update:
                    # batch-label tickets carry a q-wide list: every one
                    # of its oracle answers counts (the loadgen's
                    # double-apply sentinel reads this)
                    t.session.n_labeled += (len(t.label)
                                            if isinstance(t.label, list)
                                            else 1)
                if t.request_id is not None:
                    # idempotency: the result is committed BEFORE the
                    # ticket resolves, so a client retry racing the
                    # response can only ever read, never re-apply
                    recent = t.session.recent
                    recent[t.request_id] = r
                    while len(recent) > 128:  # bounded retry window
                        recent.pop(next(iter(recent)))
                    # identity-guarded (like _forget_pending): if a cancel
                    # of THIS ticket already let the client's retry
                    # re-register the request_id, popping here would strip
                    # the newer ticket's registration mid-flight
                    if t.session.pending.get(t.request_id) is t:
                        t.session.pending.pop(t.request_id, None)
                if self.recorder is not None:
                    row = {
                        "n_labeled": t.session.n_labeled,
                        "do_update": t.do_update,
                        "labeled_idx": t.idx if t.do_update else None,
                        "label": t.label if t.do_update else None,
                        "prob": t.prob if t.do_update else None,
                        "request_id": t.request_id,
                        "next_idx": r["next_idx"],
                        "next_prob": r["next_prob"],
                        "best": r["best"],
                        "stochastic": r["stochastic"],
                        "pbest_max": r.get("pbest_max"),
                        "pbest_entropy": r.get("pbest_entropy"),
                    }
                    if t.trace is not None:
                        # additive optional field: a decision row joins to
                        # its serving trace; absent (not null) when
                        # untraced, so tracing-off streams stay bitwise
                        # identical to pre-tracing streams
                        row["trace_id"] = t.trace.trace_id
                    if slot in pred_probs:
                        # additive optional field (same contract as
                        # trace_id): the probability the session's
                        # consensus pi_hat assigned to the realized oracle
                        # label, read pre-update — calibration needs no
                        # posterior re-read. Absent with quality off, so
                        # off-streams stay bitwise identical on the
                        # existing keys.
                        row["pred_label_prob"] = pred_probs[slot]
                    self.recorder.append(t.session.sid, row)
                if self.metrics is not None:
                    tid = t.trace.trace_id if t.trace is not None else None
                    self.metrics.record_request_latency(
                        now - t.submitted, trace_id=tid)
                    self.metrics.record_queue_wait(
                        t.collected - t.submitted, trace_id=tid)
                t.complete(r, collector=deliveries)
            for loop, items in deliveries.items():
                try:
                    loop.call_soon_threadsafe(_deliver_batch, items)
                except RuntimeError:  # loop closed; waiters are gone
                    pass
            if self.metrics is not None:
                self.metrics.record_dispatch(
                    len(slots), depth, dt,
                    step_seconds=timing.get("step_s"),
                    warm=bucket.is_warm)
        for t in requeue:
            self.queue.put(t)
        if self.faults is not None:
            self.faults.fire("tick_post")   # crash_after_tick
