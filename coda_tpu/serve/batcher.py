"""Micro-batching dispatcher: many sessions' requests, one program launch.

The host half of the serving layer. HTTP worker threads submit one request
per user action (session start, oracle label) and block on a ticket; a
single batcher thread drains the queue, coalesces everything that arrived
within a ``max_wait`` window (up to ``max_batch``), groups by bucket, and
executes ONE compiled masked slab step per bucket
(:func:`coda_tpu.serve.state.make_slab_step`). Accelerator dispatch cost is
thus amortized over every concurrent session instead of paid per click —
the standard batched-inference serving move, applied to the paper's
select/update/best loop.

Two requests for the same slot never ride one tick (the second would read
the first's pre-update state); the collision is requeued for the next tick.
Closed-loop clients can't produce collisions (they wait for their reply),
so this path only guards misbehaving open-loop callers.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Ticket:
    """One submitted request and its rendezvous."""

    session: object                 # state.Session
    do_update: bool
    idx: int = 0
    label: int = 0
    prob: float = 0.0
    submitted: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[dict] = None
    error: Optional[BaseException] = None
    cancelled: bool = False

    def wait(self, timeout: Optional[float] = None) -> dict:
        """Block for the result. On timeout the ticket is CANCELLED before
        raising: a still-queued request must not fire later against a slot
        the caller has given up on (it could have been freed and reassigned
        — the dispatch would advance another session's PRNG stream — or,
        for a label the client will retry, apply the same update twice).
        Best-effort: a ticket already inside a dispatch completes."""
        if not self.done.wait(timeout):
            self.cancelled = True
            raise TimeoutError("serve dispatch timed out")
        if self.error is not None:
            raise self.error
        return self.result


class Batcher:
    """The dispatcher thread around a :class:`SessionStore`.

    ``max_batch`` caps requests per tick; ``max_wait`` is how long the tick
    lingers after the FIRST request for stragglers to coalesce (the
    latency/occupancy dial). ``start()``/``stop()`` manage the thread;
    ``pause()``/``resume()`` freeze ticking with the queue still accepting —
    the deterministic-occupancy hook the lockstep load generator and the
    batching tests use.
    """

    def __init__(self, store, metrics=None, max_batch: int = 256,
                 max_wait: float = 0.002, telemetry=None, recorder=None):
        self.store = store
        self.metrics = metrics
        # optional Telemetry: each per-bucket dispatch becomes a span on the
        # "host:batcher" lane (annotated so a live jax.profiler capture
        # shows the same tick names next to the device rows)
        self.telemetry = telemetry
        # optional SessionRecorder: every completed ticket appends one
        # decision row to its session's record stream (the flight
        # recorder's serving face — GET /session/{id}/trace)
        self.recorder = recorder
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.queue: queue.Queue = queue.Queue()
        self._running = False
        self._paused = threading.Event()
        self._paused.set()  # set = not paused
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Batcher":
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop ticking; with ``drain`` (default) finish queued work first."""
        if self._thread is None:
            return
        if drain:
            deadline = time.perf_counter() + timeout
            while not self.queue.empty() and time.perf_counter() < deadline:
                time.sleep(0.005)
        self._running = False
        self._paused.set()
        self._thread.join(timeout=timeout)
        self._thread = None
        # fail any tickets stranded by a non-drained stop
        while True:
            try:
                t = self.queue.get_nowait()
            except queue.Empty:
                break
            t.error = RuntimeError("server stopped")
            t.done.set()

    def pause(self) -> None:
        self._paused.clear()

    def resume(self) -> None:
        self._paused.set()

    # -- submission (HTTP worker threads) ----------------------------------
    def submit(self, ticket: Ticket) -> Ticket:
        self.queue.put(ticket)
        return ticket

    def submit_start(self, session) -> Ticket:
        return self.submit(Ticket(session=session, do_update=False))

    def submit_label(self, session, idx: int, label: int,
                     prob: float) -> Ticket:
        return self.submit(Ticket(session=session, do_update=True, idx=idx,
                                  label=label, prob=prob))

    # -- the tick ----------------------------------------------------------
    def _collect(self) -> list:
        """Block for the first ticket, then linger ``max_wait`` for more.

        A pause() landing mid-collect (the thread may already hold a ticket
        from its blocking get) HOLDS the partial batch and restarts the
        linger window on resume, so everything submitted while paused still
        rides this one dispatch — without this, lockstep's
        one-dispatch-per-round guarantee would be a race against the first
        submitter."""
        try:
            first = self.queue.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        deadline = time.perf_counter() + self.max_wait
        while len(batch) < self.max_batch:
            if not self._paused.is_set():
                self._paused.wait()
                deadline = time.perf_counter() + self.max_wait
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self.queue.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _loop(self) -> None:
        while self._running:
            self._paused.wait()
            batch = self._collect()
            if not batch:
                continue
            self._dispatch(batch)

    def _dispatch(self, batch: list) -> None:
        # group by bucket; at most one ticket per slot per tick. Cancelled
        # tickets (wait-timeout) and tickets whose session closed while
        # queued are dropped HERE, not dispatched — their slot may already
        # belong to someone else (see Ticket.wait)
        per_bucket: dict = {}
        requeue: list = []
        for t in batch:
            if t.cancelled or not self.store.alive(t.session.sid):
                t.error = RuntimeError("request cancelled (timeout or "
                                       "session closed while queued)")
                t.done.set()
                continue
            slots = per_bucket.setdefault(t.session.bucket, {})
            if t.session.slot in slots:
                requeue.append(t)  # same-slot collision -> next tick
            else:
                slots[t.session.slot] = t
        depth = self.queue.qsize() + len(requeue)
        for bucket, slots in per_bucket.items():
            reqs = {
                slot: {"do_update": t.do_update, "idx": t.idx,
                       "label": t.label, "prob": t.prob}
                for slot, t in slots.items()
            }
            span = (self.telemetry.span(
                        f"tick/{bucket.task}", lane="host:batcher",
                        annotate=True, requests=len(slots), depth=depth)
                    if self.telemetry is not None
                    else contextlib.nullcontext())
            t0 = time.perf_counter()
            try:
                # the bucket lock serializes the slab swap against THIS
                # bucket's admission writes only — other buckets' dispatches
                # and admissions proceed (see SessionStore docstring)
                with span, bucket.lock:
                    results = bucket.dispatch(reqs)
            except BaseException as e:  # surface to every waiter, keep going
                for t in slots.values():
                    t.error = e
                    t.done.set()
                continue
            dt = time.perf_counter() - t0
            now = time.perf_counter()
            for slot, t in slots.items():
                t.result = results[slot]
                t.session.last = results[slot]
                if t.do_update:
                    t.session.n_labeled += 1
                if self.recorder is not None:
                    r = results[slot]
                    self.recorder.append(t.session.sid, {
                        "n_labeled": t.session.n_labeled,
                        "do_update": t.do_update,
                        "labeled_idx": t.idx if t.do_update else None,
                        "label": t.label if t.do_update else None,
                        "prob": t.prob if t.do_update else None,
                        "next_idx": r["next_idx"],
                        "next_prob": r["next_prob"],
                        "best": r["best"],
                        "stochastic": r["stochastic"],
                    })
                if self.metrics is not None:
                    self.metrics.record_request_latency(now - t.submitted)
                t.done.set()
            if self.metrics is not None:
                self.metrics.record_dispatch(len(slots), depth, dt)
        for t in requeue:
            self.queue.put(t)
