"""Tiered posterior state: hot/warm/cold session paging with wake-on-label.

Before this module a session existed only while it held a device slab
slot, so the open-session ceiling was the slab capacity and admission past
it answered 503 — even though production traffic is Zipf-shaped and most
sessions idle most of the time. The fix treats posterior state as a paged
cache hierarchy (the direction arXiv 2202.10522 takes for NVM-accelerated
posterior estimation):

  * **hot** — resident in a device slab slot (`serve/state.py`), served by
    the batched masked step exactly as before;
  * **warm** — demoted to a host-RAM export payload: the SAME
    digest-verified serialization `POST /session/{id}/export` produces
    (`recovery.build_export_payload`), minus the HTTP hop. The slab slot
    is freed; the recorder stream is *parked* (fd closed, in-memory
    history dropped — the payload carries the rows) but NOT closed, so a
    crash still restores the session from its stream;
  * **cold** — hibernated to disk: the payload lands in the spill dir's
    append-log store (``serve/spill.py`` — zlib-compressed frames + an
    in-memory index, compacted at startup; the v1 one-file-per-session
    layout is still readable) and the recorder stream gets its close
    marker (the spill store is now the authority; ``--restore`` must not
    double-restore it). A restarted TierManager re-indexes the spill
    log, so cold sessions survive process death.

In a replica fleet (``serve/fleet.py``) the warm→cold transition gets a
third option: when a ``page_out`` hook is installed, a watermark- or
age-pressured warm session is offered to a less-loaded PEER replica
first (the payload imports there, digest-verified, and the fleet router
re-points the sid) and only hits the local disk when no peer takes it.

A label, ``best``, or ``trace`` arriving for a non-resident session
transparently **wakes** it through the import fast path — snapshot
restore accepted on a bitwise posterior-digest match against the stream's
last recorded digest, stream replay only as the fallback — instead of
404/503. Admission past capacity becomes "demote the coldest, then
admit" (:meth:`TierManager.make_room`) instead of ``SlabFull`` → 503.

Race rules (the part that must be exactly right):

  * every session verb holds a **pin** (``Session.pins``, taken atomically
    with the store lookup) for its whole slab interaction — a label
    ticket's pin lives until the ticket resolves. Demotion snapshots the
    session, then atomically re-checks ``pins == 1 (ours)`` and
    ``n_labeled`` unchanged under the store lock before unpublishing the
    sid; any in-flight ticket or completed label makes demotion LOSE
    cleanly (abort, state untouched) — never a lost or double-applied
    label, never a ticket dispatched into a freed slot.
  * wake rides the existing staged lock-free admission (`Bucket.allocate`
    + `restore_slot` stage their slab writes), so a thundering herd of
    wakes never convoys the dispatch lock; concurrent wakes of the SAME
    sid coalesce on one waker (the rest wait on its event).
  * demotion vs ``POST /export``: export pins too, so a demotion either
    completes before the export (which then serves the parked payload
    directly) or aborts — the payload a client receives is always a
    consistent snapshot of a quiescent posterior.

Observability: ``sessions_hot/warm/cold`` gauges, ``demotions/wakes/
hibernates_total`` counters, and a wake-latency ring (p50/p99) ride
``/stats`` and ``/metrics`` (`serve/metrics.py`); the sweeper samples
``process_rss_bytes`` so the ≥100k-session RSS claim is gateable
(`scripts/check_perf.py`, ``BENCH_TIERED_*``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

from coda_tpu.serve.spill import LEGACY_PREFIX as _HIB_PREFIX  # noqa: F401
from coda_tpu.serve.spill import SpillStore
from coda_tpu.serve.state import SlabFull, UnknownSession


class TierManager:
    """Hot/warm/cold paging policy + mechanics around one ServeApp.

    ``spill_dir`` enables the cold tier (None = warm-only paging).
    ``idle_warm_s`` / ``idle_cold_s`` drive idle demotion (hot→warm) and
    hibernation (warm→cold); ``max_warm`` bounds host-RAM payloads (LRU
    overflow hibernates); ``free_fraction`` > 0 makes the sweeper keep
    that fraction of each slab free ahead of admission bursts (watermark
    demotion — LRU on last-label time, only sessions idle at least
    ``min_idle_s`` so a briefly-paused closed-loop client is never paged
    out under it). Admission-pressure demotion (:meth:`make_room`) has no
    idle floor — when the alternative is 503, the coldest session goes.
    """

    def __init__(self, app, spill_dir: Optional[str] = None,
                 idle_warm_s: float = 30.0, idle_cold_s: float = 120.0,
                 max_warm: int = 8192, free_fraction: float = 0.0,
                 sweep_interval_s: float = 0.25, min_idle_s: float = 1.0,
                 wake_attempts: int = 16):
        self.app = app
        self.spill_dir = spill_dir
        self.idle_warm_s = float(idle_warm_s)
        self.idle_cold_s = float(idle_cold_s)
        self.max_warm = int(max_warm)
        self.free_fraction = float(free_fraction)
        self.sweep_interval_s = float(sweep_interval_s)
        self.min_idle_s = float(min_idle_s)
        self.wake_attempts = int(wake_attempts)
        # tier maps: sid -> {payload, task, last_used} (warm, LRU-ordered)
        # and the cold append-log store (spill.py, its own sid index).
        # _waking holds one event per in-flight wake so a thundering herd
        # of requests for one sid rides a single restore.
        self._lock = threading.Lock()
        self._warm: "OrderedDict[str, dict]" = OrderedDict()
        self._waking: dict[str, threading.Event] = {}
        self.spill_errors = 0        # hibernate writes that failed (stayed warm)
        # fleet hook (serve/fleet.py): page_out(sid, payload) -> bool
        # offers a warm payload to a less-loaded peer replica before the
        # disk; True = the peer imported it (digest-verified) and owns it
        # (counted in ServeMetrics.peer_pages + the router's counter)
        self.page_out = None
        self._running = False
        self._wakeup = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the cold tier: append-log + index + compression; re-indexes (and
        # startup-compacts) a previous incarnation's log AND any v1
        # hibernated_<sid>.json files, so cold sessions survive process
        # death across both layouts
        self._spill = SpillStore(spill_dir) if spill_dir else None
        if self._spill is not None:
            # the v3 store's segment/index/compaction gauges ride every
            # /stats and /metrics snapshot (read on demand, no sync)
            self.app.metrics.spill_provider = self._spill.stats

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TierManager":
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-tier-sweeper")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        self._wakeup.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if self._spill is not None:
            # flush the sidecar index so the next start is O(index)
            self._spill.close()

    # -- reads -------------------------------------------------------------
    def counts(self) -> dict:
        with self._lock:
            warm = len(self._warm)
        cold = len(self._spill) if self._spill is not None else 0
        return {"hot": self.app.store.live_sessions(), "warm": warm,
                "cold": cold}

    def parked(self, sid: str) -> bool:
        """Whether the sid lives in a non-resident tier (or is mid-wake)."""
        with self._lock:
            if sid in self._warm or sid in self._waking:
                return True
        return self._spill is not None and sid in self._spill

    def parked_sids(self) -> list[str]:
        """Every non-resident session id, warm first then cold — the one
        tier-union enumeration (``/sessions``, ``export_parked``, the
        fleet worklist all read this, so the tier map layout has a
        single reader)."""
        with self._lock:
            sids = list(self._warm)
        if self._spill is not None:
            seen = set(sids)
            sids += [s for s in self._spill.sids() if s not in seen]
        return sids

    def parked_payload(self, sid: str) -> Optional[dict]:
        """The export payload of a parked session, without waking it (the
        migration sweep and ``POST /export`` read this directly — a warm
        session IS its payload). None when the sid is hot, mid-wake, or
        unknown."""
        with self._lock:
            entry = self._warm.get(sid)
            if entry is not None:
                return entry["payload"]
        if self._spill is None:
            return None
        from coda_tpu.serve.spill import materialize

        # the export/migration surfaces serialize this: hand them a
        # plain JSON-safe dict, not the store's lazy mmap view
        return materialize(self._spill.get(sid))

    def export_parked(self) -> list:
        """Every parked session's payload (the drain/migrate sweep's
        off-slab half — rolling restarts must carry all three tiers)."""
        out = []
        for sid in self.parked_sids():
            p = self.parked_payload(sid)
            if p is not None:
                out.append(p)
        return out

    # -- demotion (hot -> warm) --------------------------------------------
    def try_demote(self, sid: str) -> bool:
        """Demote one resident session to the warm tier; False when it
        cannot be demoted RIGHT NOW (unknown, restoring, pinned by an
        in-flight verb/ticket, no completed first dispatch, or a label
        landed while the payload was being built). Losing those races is
        the contract, not a failure — the caller just moves on."""
        with self.app.store.lock:
            sess = self.app.store._sessions.get(sid)
            bucket = sess.bucket if sess is not None else None
        if bucket is None:
            return False
        return self.demote_batch(bucket, [sid]) == 1

    def demote_batch(self, bucket, sids, allow_unstarted: bool = False
                     ) -> int:
        """Demote many of one bucket's sessions in one sweep: candidates
        are pinned, the slab is snapshotted ONCE for all of them
        (`Bucket.snapshot_slots` — one lock acquisition instead of one
        per session), and each is then atomically unpublished under the
        same pins-and-label-count re-check as a single demotion. Returns
        how many demoted; each loser aborted cleanly with its state
        untouched.

        ``allow_unstarted`` admits sessions with no completed dispatch
        (``sess.last`` empty) — the restore-wave path only, where a
        zero-row stream legitimately restores to that state; live
        traffic keeps the guard because a brand-new open's session is
        briefly unpinned before its start ticket is submitted."""
        from coda_tpu.serve import recovery

        app, store = self.app, self.app.store
        cands = []
        with store.lock:
            for sid in sids:
                sess = store._sessions.get(sid)
                if sess is None or sess.bucket is not bucket \
                        or sess.restoring or sess.pins > 0 \
                        or (not sess.last and not allow_unstarted):
                    continue
                sess.pins += 1          # our own pin: blocks other demoters
                cands.append((sess, sess.n_labeled))
        if not cands:
            return 0
        try:
            snaps = bucket.snapshot_slots([s.slot for s, _ in cands])
        except Exception:
            snaps = {}  # slab unreadable (quarantined, ...): all abort
        n_demoted = 0
        for sess, n0 in cands:
            published = False
            try:
                snap = snaps.get(sess.slot)
                if snap is None:
                    continue
                payload = recovery.build_export_payload(app, sess,
                                                        snapshot=snap)
                with store.lock:
                    if sess.pins != 1 or sess.n_labeled != n0:
                        # an in-flight ticket holds a pin, or a label
                        # committed since the snapshot: demotion loses
                        continue
                    if store._sessions.pop(sess.sid, None) is None:
                        # closed concurrently (close never pins): the
                        # session is gone, nothing to demote
                        continue
                    sess.pins = 0
                    published = True
            except Exception:
                continue  # only THIS candidate aborts — an escape here
                #           would strand the remaining candidates pinned
            finally:
                if not published:
                    store.unpin(sess)
            if not published:
                continue
            # from here no verb can reach the session (get raises):
            # release the slot, park the stream, publish the payload
            sess.bucket.release(sess.slot)
            if getattr(app, "prior_pool", None) is not None:
                # the demotion snapshot is the last host view of the fit:
                # contribute it now (close of a parked session never wakes)
                try:
                    if app.contribute_prior(sess,
                                            bucket.fit_from_leaves(snap[0])):
                        # the payload was built pre-contribution: mark it
                        # so a wake (or a migration of the parked copy)
                        # restores the once-flag and never re-contributes
                        payload["prior_contributed"] = True
                except Exception:
                    pass
            app.recorder.park(sess.sid)
            with self._lock:
                self._warm[sess.sid] = {"payload": payload,
                                        "task": sess.task,
                                        "last_used": time.monotonic()}
            app.metrics.record_tier("demote")
            n_demoted += 1
        if n_demoted:
            self._publish_gauges()
        return n_demoted

    def make_room(self, bucket) -> bool:
        """Admission-pressure demotion: page out the coldest demotable
        sessions on ``bucket`` (LRU on last-label/last-touch time). True
        when at least one slot was freed. Demotes a small LRU batch, not
        one session — the slab snapshot behind a demotion waits out any
        in-flight dispatch, so under an admission herd the wait must buy
        more than one slot."""
        sessions = self.app.store.sessions_on(bucket)
        sessions.sort(key=lambda s: s.last_used)
        batch = max(1, bucket.capacity // 16)
        while sessions:
            lru, sessions = sessions[:batch], sessions[batch:]
            if self.demote_batch(bucket, [s.sid for s in lru]) > 0:
                return True
        return False

    def make_room_for(self, task: str, spec) -> bool:
        for b in self.app.store.buckets():
            if b.task == task and b.spec == spec:
                if self.make_room(b):
                    return True
        return False

    # -- hibernation (warm -> cold) ----------------------------------------
    def hibernate(self, sid: str) -> bool:
        """Move one warm payload into the spill store. Compression runs
        OUTSIDE the tier lock (the old end-to-end hold stalled concurrent
        wakes behind zlib for the whole demotion batch); the commit
        window re-checks the entry is the SAME object — a wake or a
        re-park between the two lock windows aborts the move, so the sid
        is never unreachable and never spilled stale. A failed disk
        write leaves the session warm, counted, never lost."""
        if self._spill is None:
            return False
        with self._lock:
            entry = self._warm.get(sid)
        if entry is None:
            return False
        encoded = self._spill.encode(entry["payload"])
        with self._lock:
            if self._warm.get(sid) is not entry:
                return False  # woke (or was re-parked fresh) mid-encode
            if not self._spill.put_encoded(sid, encoded):
                self.spill_errors += 1
                return False
            del self._warm[sid]
        # the spilled frame is now the authority: seal the recorder
        # stream (close marker) so --restore skips it instead of
        # rebuilding a second live copy next to the cold one
        self.app.recorder.seal(sid)
        self.app.metrics.record_tier("hibernate")
        self._publish_gauges()
        return True

    def page_to_peer(self, sid: str) -> bool:
        """Offer one warm payload to a peer replica via the fleet's
        ``page_out`` hook (demotion-aware peer paging): the entry leaves
        the warm map FIRST (atomically — a concurrent wake then misses
        locally and the router finds the session on the peer), the peer
        imports it digest-verified, and on any failure the entry is
        re-parked warm, never lost. The local stream gets its close
        marker exactly like a migration away — the peer owns the session
        now."""
        hook = self.page_out
        if hook is None:
            return False
        with self._lock:
            entry = self._warm.pop(sid, None)
        if entry is None:
            return False
        ok = False
        try:
            ok = bool(hook(sid, entry["payload"]))
        except Exception:
            ok = False
        if not ok:
            with self._lock:
                self._warm[sid] = entry
            return False
        self.app.recorder.seal(sid)
        self.app.metrics.record_tier("peer_page")
        self._publish_gauges()
        return True

    # -- wake (warm/cold -> hot) -------------------------------------------
    def wake_if_parked(self, sid: str, timeout: float = 60.0) -> bool:
        """Wake a parked session (or wait out a wake already in flight).
        False when the sid is in no tier — the caller's UnknownSession
        stands. Raises what the wake raised (SlabFull when no slot could
        be freed, ImportRejected when the payload cannot be verified)."""
        if self.app.held(sid):
            # mid-migration (serve/server.py hold protocol): the export
            # payload is in the router's hands — a wake now would revive
            # a copy the destination may already own. Retryable: the
            # move commits (retry re-routes) or aborts (retry lands).
            from coda_tpu.serve.state import BucketQuarantined

            raise BucketQuarantined(
                f"session {sid} is migrating; retry shortly")
        with self._lock:
            ev = self._waking.get(sid)
            if ev is not None:
                mine = False
            else:
                if sid not in self._warm and not (
                        self._spill is not None and sid in self._spill):
                    return False
                ev = self._waking[sid] = threading.Event()
                mine = True
        if not mine:
            ev.wait(timeout)  # coalesced: ride the in-flight wake
            return True
        try:
            self._wake(sid)
        finally:
            with self._lock:
                self._waking.pop(sid, None)
            ev.set()
        return True

    def _wake(self, sid: str) -> None:
        """One wake: pop the payload, admit through the import fast path
        (snapshot digest-match; stream replay fallback), demoting the
        coldest resident session when the slab is full."""
        from coda_tpu.serve import recovery

        t0 = time.perf_counter()
        with self._lock:
            entry = self._warm.pop(sid, None)
        payload = None
        if entry is not None:
            src, payload = "warm", entry["payload"]
        elif self._spill is not None:
            src, payload = "cold", self._spill.get(sid)
        if payload is None:
            return  # discarded between the caller's check and ours
        try:
            info = None
            for _ in range(self.wake_attempts):
                try:
                    info = recovery.import_session(self.app, payload,
                                                   count=False)
                    break
                except SlabFull:
                    if not self.make_room_for(payload["task"],
                                              self.app.spec):
                        # every resident session is pinned by an in-flight
                        # verb: brief, retry after a beat
                        time.sleep(0.005)
            if info is None:
                raise SlabFull(
                    f"wake of session {sid}: no slab slot could be freed "
                    f"after {self.wake_attempts} demotion attempts")
        except BaseException:
            # keep the session reachable: re-park the payload (warm) /
            # leave the hibernate file (cold), and kick the healer in
            # case a replay dispatch quarantined the bucket
            if src == "warm":
                with self._lock:
                    self._warm[sid] = entry
            self.app.metrics.record_tier("wake_failed")
            self.app._heal_quarantined()
            raise
        if src == "cold":
            self._spill.delete(sid)
        self.app.metrics.record_tier(
            "wake", src=src, seconds=time.perf_counter() - t0,
            via=(info or {}).get("restored_via"))
        self._publish_gauges()

    # -- discard (close of a parked session) -------------------------------
    def discard(self, sid: str) -> bool:
        """Drop a parked session (its DELETE): payload and hibernate file
        go away; the caller writes the stream's close marker."""
        with self._lock:
            had_warm = self._warm.pop(sid, None) is not None
        had_cold = (self._spill is not None and self._spill.delete(sid))
        if had_warm or had_cold:
            self._publish_gauges()
            return True
        return False

    # -- the sweeper -------------------------------------------------------
    def _loop(self) -> None:
        while self._running:
            try:
                self.sweep()
            except Exception:
                pass  # the sweeper must never die to a transient race
            self._wakeup.wait(self.sweep_interval_s)
            self._wakeup.clear()

    def sweep(self) -> dict:
        """One pass of the demotion policy: idle hot→warm, watermark
        hot→warm (LRU, only past ``min_idle_s``), aged/overflow warm→cold.
        Returns counts (the test hook); also refreshes the tier gauges and
        the process-RSS sample the memory claim is gated on."""
        now = time.monotonic()
        store = self.app.store
        n_demoted = n_hibernated = 0
        for bucket in store.buckets():
            sessions = store.sessions_on(bucket)
            idle = [s.sid for s in sessions
                    if now - s.last_used > self.idle_warm_s]
            if idle:
                n_demoted += self.demote_batch(bucket, idle)
            if self.free_fraction > 0:
                target = max(1, int(bucket.capacity * self.free_fraction))
                deficit = target - (bucket.capacity - bucket.live)
                if deficit > 0:
                    cands = sorted(store.sessions_on(bucket),
                                   key=lambda s: s.last_used)
                    lru = [s.sid for s in cands[:deficit]
                           if now - s.last_used >= self.min_idle_s]
                    if lru:
                        n_demoted += self.demote_batch(bucket, lru)
        if self._spill is not None or self.page_out is not None:
            with self._lock:
                aged = [sid for sid, e in self._warm.items()
                        if now - e["last_used"] > self.idle_cold_s]
                over = len(self._warm) - self.max_warm
                if over > 0:
                    # LRU overflow: insertion order ≈ demotion order
                    aged_set = set(aged)
                    lru = [sid for sid in self._warm
                           if sid not in aged_set][:over]
                else:
                    lru = []
            for sid in aged + lru:
                if self.app.held(sid):
                    continue  # mid-migration: the router owns this move
                # demotion-aware peer paging: a pressured replica offers
                # the payload to a less-loaded peer first; disk is the
                # fallback, not the only exit
                if self.page_to_peer(sid):
                    n_hibernated += 1
                    continue
                n_hibernated += self.hibernate(sid)
        if self._spill is not None:
            # per-segment compaction rides the sweeper, not startup —
            # it copies raw frame bytes forward one short lock window at
            # a time, so it never stops wakes or demotions
            self._spill.maybe_compact()
        self._publish_gauges()
        from coda_tpu.telemetry.registry import sample_process_rss

        sample_process_rss(self.app.telemetry.registry)
        return {"demoted": n_demoted, "hibernated": n_hibernated}

    def _publish_gauges(self) -> None:
        c = self.counts()
        self.app.metrics.set_tier_occupancy(c["hot"], c["warm"], c["cold"])
