"""Cross-session surrogate prior pool (``--surrogate-prior pool``).

Every session that runs the surrogate scorer rung carries a private
:class:`~coda_tpu.selectors.surrogate.SurrogateFit` seeded from zeros and
pays :data:`~coda_tpu.selectors.surrogate.SURROGATE_WARMUP_ROUNDS` exact
rounds before the first surrogate-scored round can even be proposed. At
serve scale that warmup tax dominates cold-start cost — and it buys
nothing that a PREVIOUS session on the same (task, pool) did not already
pay for, because the fit is a ridge regression in normal-equation form:
its sufficient statistics ``(A = ΣFᵀF, b = ΣFᵀy, n)`` are pure sums,
mergeable across sessions by construction.

This module is the serve-side pool of those statistics:

  * sessions CONTRIBUTE at close and at demotion (exactly once each —
    ``Session.prior_contributed``), only when their fit saw at least
    :data:`~coda_tpu.selectors.surrogate.SURROGATE_PRIOR_MIN_ROUNDS`
    audited rounds;
  * new sessions SEED from the merged pool (``Bucket.set_prior`` →
    admission applies :func:`~coda_tpu.selectors.surrogate.seed_fit`),
    which grants warmup credit — but the per-round trust gate (escape
    hatch, audit rank, the score contract) is unchanged, so a selection
    is still never driven by an unaudited score: a prior that transfers
    badly fails its audits, increments ``prior_rejects`` on the slab
    carry, and the session falls back to exact scoring exactly as a
    cold session would;
  * replicas EXCHANGE deltas through the router, piggybacked on the
    health poll (serve/router.py): each poll drains the replica's
    since-last-poll contributions, folds them into the router's global
    pool, and pushes the merged pool back — replicas REPLACE their pool
    with the router's so a contribution is never double-counted;
  * the pool SURVIVES restart via the tracking store
    (``log_artifact_bytes`` of :meth:`PriorPool.snapshot`).

Pools are keyed per (task, pool fingerprint): dataset digest + selector
method + spec kwargs MINUS the knobs that do not change the feature
space (the scorer's ``k``, ``surrogate_prior`` itself, ``acq_batch`` —
a q=8 session's fit statistics live in the same 16-feature space as a
q=1 session's and transfer across).

Staleness evidence (r20): the pool timestamps every per-key touch
(contribute / merged delta / adopted snapshot) so ``/stats`` and
``/metrics`` carry ``prior_pool_staleness_seconds`` (the age of the
LEAST recently refreshed pool) and per-pool contribution ages — one half
of the learned-decay sensor the ROADMAP asks for; the decision-quality
plane's ``prior_staleness`` drift detector and the shadow auditor's
seeded-vs-cold gap (``telemetry/quality.py``) are the other half.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Callable, Optional

from coda_tpu.selectors.surrogate import (
    SURROGATE_PRIOR_DECAY,
    SURROGATE_PRIOR_MIN_ROUNDS,
    PriorStats,
    empty_prior,
    fold_prior,
    merge_fits,
    prior_from_dict,
    prior_from_fit,
    prior_to_dict,
)

#: spec kwargs that do NOT change the fit's feature space — excluded
#: from the pool fingerprint so statistics transfer across them
_FINGERPRINT_EXCLUDED = ("eig_scorer", "surrogate_prior", "acq_batch")


def pool_key(task: str, method: str, spec_kwargs, dataset_digest) -> str:
    """Stable pool key: task + dataset digest + method + the kwargs that
    shape the feature space."""
    kept = sorted((str(k), str(v)) for k, v in (spec_kwargs or ())
                  if str(k) not in _FINGERPRINT_EXCLUDED)
    h = hashlib.blake2b(digest_size=8)
    h.update(json.dumps([task, str(dataset_digest), method, kept],
                        separators=(",", ":")).encode())
    return f"{task}:{h.hexdigest()}"


def bucket_pool_key(app, bucket) -> str:
    """The pool key of one serve bucket (its task's dataset digest is in
    the store's task meta)."""
    meta = app.store.task_meta(bucket.task)
    return pool_key(bucket.task, bucket.spec.method, bucket.spec.kwargs,
                    meta.get("digest"))


class PriorPool:
    """Thread-safe map of pool key -> merged :class:`PriorStats`, plus
    the since-last-drain delta the router exchange ships.

    ``clock`` is injectable (wall-clock seconds) so staleness tests
    drive synthetic ages without sleeping."""

    def __init__(self, decay: float = SURROGATE_PRIOR_DECAY,
                 min_rounds: float = SURROGATE_PRIOR_MIN_ROUNDS,
                 clock: Callable[[], float] = time.time):
        self.decay = float(decay)
        self.min_rounds = float(min_rounds)
        self._clock = clock
        self._lock = threading.Lock()
        self._pools: dict[str, PriorStats] = {}
        self._delta: dict[str, PriorStats] = {}
        # key -> wall-clock second of the last statistic fold (the
        # staleness axis a learned decay schedule regresses against)
        self._touched: dict[str, float] = {}
        self.sessions_contributed = 0   # accepted contributions
        self.contributions_skipped = 0  # below min_rounds / degenerate

    # -- contribution ------------------------------------------------------
    def contribute(self, key: str, fit_stats: Optional[dict]) -> bool:
        """Fold one session's fit statistics (``{"A","b","n","rounds"}``
        — Bucket.fit_from_leaves' output, or a host read of the slot
        fit) into the pool. False (counted) when the fit is too green to
        teach anything: fewer than ``min_rounds`` audited rounds, or a
        degenerate pair count."""
        if fit_stats is None:
            return False
        try:
            rounds = float(fit_stats["rounds"])
            contrib = prior_from_fit(fit_stats["A"], fit_stats["b"],
                                     fit_stats["n"], rounds)
        except (KeyError, TypeError, ValueError):
            self.contributions_skipped += 1
            return False
        if rounds < self.min_rounds or contrib.n <= 0:
            self.contributions_skipped += 1
            return False
        with self._lock:
            self._pools[key] = fold_prior(
                self._pools.get(key, empty_prior()), contrib,
                decay=self.decay)
            # the delta is the raw sum of contributions since the last
            # drain — the router applies its own fold (decay + clip) when
            # it merges, so decay is never applied twice to one statistic
            self._delta[key] = merge_fits(
                self._delta.get(key, empty_prior()), contrib)
            self._touched[key] = self._clock()
            self.sessions_contributed += 1
        return True

    # -- seeding reads -----------------------------------------------------
    def get(self, key: str) -> Optional[PriorStats]:
        with self._lock:
            p = self._pools.get(key)
        if p is None or p.n <= 0 or p.rounds < self.min_rounds:
            # a pool that has seen less than one full warmup's worth of
            # audited rounds grants no credit worth recording
            return None
        return p

    def keys(self) -> list:
        with self._lock:
            return sorted(self._pools)

    # -- router exchange ---------------------------------------------------
    def drain_delta(self) -> dict:
        """The contributions since the last drain, JSON-safe; clears the
        delta (the replica side of the health-poll piggyback)."""
        with self._lock:
            delta, self._delta = self._delta, {}
        return {k: prior_to_dict(p) for k, p in delta.items()}

    def merge_delta(self, delta: dict, count: bool = True) -> int:
        """Fold a drained delta into this pool (the ROUTER side: one
        fold per drain, so each contribution is decayed once here).
        ``count=False`` skips the sessions_contributed bump — the
        replica's re-fold of its OWN just-drained delta after a pool
        push (sync_prior), where contribute() already counted it."""
        n = 0
        for key, d in (delta or {}).items():
            try:
                contrib = prior_from_dict(d)
            except (KeyError, TypeError, ValueError):
                continue
            if contrib.n <= 0:
                continue
            with self._lock:
                self._pools[key] = fold_prior(
                    self._pools.get(key, empty_prior()), contrib,
                    decay=self.decay)
                self._touched[key] = self._clock()
                if count:
                    self.sessions_contributed += max(
                        1, int(contrib.sessions))
            n += 1
        return n

    # -- persistence / replacement ----------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe full-pool snapshot (tracking-store persistence and
        the router's push half of the exchange). ``touched`` carries the
        per-key contribution timestamps so staleness survives the
        exchange/restart round-trip — a pool that comes back from the
        router is as old as its statistics, not reborn at adoption."""
        with self._lock:
            return {"v": 1,
                    "sessions_contributed": self.sessions_contributed,
                    "touched": dict(self._touched),
                    "pools": {k: prior_to_dict(p)
                              for k, p in self._pools.items()}}

    def replace(self, snap: dict) -> int:
        """Adopt a snapshot wholesale (the REPLICA side of the exchange,
        and restart restore): replacing — not merging — is what keeps a
        replica's own just-drained contributions from double-counting
        when the router's merged pool comes back."""
        pools = {}
        for key, d in (snap or {}).get("pools", {}).items():
            try:
                pools[key] = prior_from_dict(d)
            except (KeyError, TypeError, ValueError):
                continue
        touched_in = (snap or {}).get("touched") or {}
        now = self._clock()
        with self._lock:
            self._pools = pools
            # keep the snapshot's ages where it has them; a key the
            # snapshot never timestamped (pre-r20 snapshot) reads as
            # touched now — fresh-by-assumption beats infinitely-stale
            self._touched = {
                key: float(touched_in[key])
                if isinstance(touched_in.get(key), (int, float)) else now
                for key in pools
            }
            n = len(pools)
            sc = (snap or {}).get("sessions_contributed")
            if isinstance(sc, (int, float)):
                self.sessions_contributed = max(
                    self.sessions_contributed, int(sc))
        return n

    # -- staleness ---------------------------------------------------------
    def pool_ages(self) -> dict:
        """Per-pool seconds since the last statistic fold."""
        now = self._clock()
        with self._lock:
            return {key: max(0.0, now - t)
                    for key, t in self._touched.items()}

    def staleness_seconds(self) -> Optional[float]:
        """Age of the LEAST recently refreshed pool (None when empty) —
        the scalar ``prior_pool_staleness_seconds`` gauge: the worst-case
        decay target a learned schedule has to answer for."""
        ages = self.pool_ages()
        return max(ages.values()) if ages else None

    def stats(self) -> dict:
        ages = self.pool_ages()
        with self._lock:
            return {
                "pools": len(self._pools),
                "sessions_contributed": self.sessions_contributed,
                "contributions_skipped": self.contributions_skipped,
                "pending_delta": len(self._delta),
                "rounds_pooled": float(sum(p.rounds
                                           for p in self._pools.values())),
                "staleness_seconds": (max(ages.values()) if ages else None),
                "pool_ages_seconds": {k: round(v, 3)
                                      for k, v in sorted(ages.items())},
            }
