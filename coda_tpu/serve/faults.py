"""Deterministic fault injection for the serving layer.

Every recovery path in ``serve/recovery.py`` exists because some step of
the serving pipeline can fail: the compiled slab step can raise after
consuming its donated carries, it can return NaN (silent posterior
corruption), the recorder's disk can fill, the host can stall, the process
can die between ticks. None of those are reachable from a test without
help — so this module makes each one *injectable*, deterministically, at
the exact site where it would occur in production. The fault matrix
(``scripts/check_fault_matrix.py``) and the loadgen chaos mode
(``--fault-spec``) then exercise every recovery path instead of reasoning
about it.

Spec grammar (``--fault-spec``), semicolon-separated faults::

    <name>:<param>=<value>[,<param>=<value>...][;<name>:...]

Names (each is one injection point):

  * ``step_raise``    — the slab step raises AFTER the executable has run
                        (donated carries are already consumed — the
                        quarantine/self-heal path);
  * ``step_nan``      — the step's outputs (next_prob + P(best) digest)
                        are replaced with NaN (silent-corruption path: the
                        digest verification must catch it);
  * ``record_eio``    — the recorder's stream write raises ``OSError``
                        (disk-full path: degrade to memory-only stream);
  * ``slow_step``     — the dispatch sleeps ``ms`` before the step (tail
                        amplification; also the concurrent-export race
                        window);
  * ``crash_before_tick`` / ``crash_after_tick`` — ``os._exit(17)``
                        around a batcher tick (crash-restore path: rebuild
                        sessions from their JSONL streams);
  * ``demote_during_label`` — a tier demotion is attempted at the exact
                        moment a label arrives for the session
                        (demotion-vs-ticket race: either the label wakes
                        the freshly-demoted session or the demotion loses
                        cleanly to the in-flight pin);
  * ``oracle_poison``  — an arriving crowd answer (``POST /session/{id}/
                        answer``) is corrupted to the adversarial family
                        ``(label+1) % C`` before parking;
  * ``oracle_abstain`` — an arriving crowd answer is converted into an
                        abstention (the slot stays open).

Fleet-level names (fired inside the router↔replica transport,
``serve/transport.py``, addressable per edge with ``edge=<replica_id>``
and per verb with ``task=<verb>``):

  * ``net_drop``      — the call raises ``ConnectionError`` before the
                        send (a lost packet; retry/breaker territory);
  * ``partition``     — same drop, but idiomatically used with
                        ``times=K`` for a K-arrival outage window that
                        "heals" when the budget is spent;
  * ``net_delay``     — the edge sleeps ``ms`` before the send (tail
                        amplification across the fleet);
  * ``net_dup``       — the request is DELIVERED TWICE (a retransmitted
                        packet): the second answer is discarded and the
                        replica's request_id dedupe must keep the
                        posterior exactly-once;
  * ``flap_healthz``  — the health probe answers unready without
                        touching the replica (the eviction-hysteresis
                        scenario);
  * ``kill_replica``  — fired at the router's mid-migration point
                        (between export and import): the matching
                        replica is killed abruptly via the fleet's kill
                        hook — SIGKILL semantics for the in-process
                        fleet.

Triggers (deterministic — a spec plus a request history replays exactly):

  * ``after=N``  — fire on the (N+1)-th arrival at the site (0-indexed),
                   ``times=K`` fires on the K arrivals from there
                   (default 1);
  * ``every=N``  — fire on every N-th arrival (unbounded unless ``times``);
  * ``p=F,seed=S`` — fire when a counter-addressed hash draw < F: the
                   decision for arrival ``i`` is a pure function of
                   (seed, name, i), so two runs with the same spec and
                   arrival order inject identically ("seed-addressable");
  * ``task=T``   — only fire for that bucket/task (default all).

Example: ``step_raise:after=5;slow_step:every=3,ms=20``.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

#: injection-point name -> site it hooks (documentation + validation).
FAULT_SITES = {
    "step_raise": "step_post",      # after the executable ran (carries gone)
    "step_nan": "step_out",         # corrupt the step's host outputs
    "record_eio": "record_write",   # inside SessionRecorder.append
    "slow_step": "step_pre",        # before the step, inside the lock
    "crash_before_tick": "tick_pre",
    "crash_after_tick": "tick_post",
    # inject a tier demotion at the exact moment a label arrives for the
    # session (serve/tiering.py): either the demotion wins and the label
    # transparently wakes the session back, or it loses cleanly to an
    # in-flight pin — the matrix fails on any lost/double-applied label
    "demote_during_label": "label_pre",
    # fleet-level faults (serve/transport.py fires these per
    # router↔replica edge; filter with edge=<replica_id> / task=<verb>)
    "net_drop": "edge_call",
    "partition": "edge_call",
    "net_delay": "edge_call",
    "net_dup": "edge_call",
    "flap_healthz": "edge_healthz",
    # process fault: fired by the router between a migration's export
    # and its import (serve/router.py); the fleet's kill hook SIGKILLs
    # the matching replica at exactly that point
    "kill_replica": "migrate_mid",
    # crowd-oracle answer faults (fired by ServeApp.answer, applied
    # OUT-OF-BAND by the answer path itself): oracle_poison corrupts the
    # arriving label to the adversarial family ((label+1) % C — the
    # systematic mislabeler of coda_tpu/crowd/oracle.py), oracle_abstain
    # converts the answer into an abstention (the slot stays open). The
    # robustness matrix drives both through the front door to show the
    # parking + dedupe layer keeps labels exactly-once regardless.
    "oracle_poison": "oracle_answer",
    "oracle_abstain": "oracle_answer",
    # decision-quality plane (telemetry/quality.py): fired by the shadow
    # auditor just before it replays a sampled session's stream, applied
    # OUT-OF-BAND — the auditor ulp-tampers its in-memory COPY of the
    # rows (the session's real stream is untouched), so the bench can
    # prove a single-ulp stream corruption is caught and attributed to
    # the exact session + round
    "stream_tamper": "audit_pre",
}

_CRASH_EXIT_CODE = 17  # distinguishable from python tracebacks (1) in tests


class FaultInjected(RuntimeError):
    """An injected fault fired (never raised by real failures)."""


@dataclass
class _Fault:
    """One parsed fault: a name, a trigger, and a fire budget."""

    name: str
    site: str
    after: Optional[int] = None
    every: Optional[int] = None
    p: Optional[float] = None
    seed: int = 0
    times: Optional[int] = None     # max fires; default 1 for `after`
    ms: float = 0.0                 # slow_step / net_delay only
    task: Optional[str] = None      # bucket filter (verb at edge sites)
    edge: Optional[str] = None      # router↔replica edge filter
    count: int = 0                  # arrivals at the site (matching task)
    fired: int = 0

    def should_fire(self) -> bool:
        """Decide for the CURRENT arrival (caller already bumped count)."""
        i = self.count - 1
        budget = self.times if self.times is not None else (
            1 if self.after is not None else None)
        if budget is not None and self.fired >= budget:
            return False
        if self.after is not None:
            return i >= self.after
        if self.every is not None:
            return self.every > 0 and (i + 1) % self.every == 0
        if self.p is not None:
            # counter-addressed hash draw: deterministic per (seed, name, i)
            h = hashlib.sha256(
                f"{self.seed}:{self.name}:{i}".encode()).digest()
            draw = int.from_bytes(h[:8], "big") / float(1 << 64)
            return draw < self.p
        return True  # bare fault: fire on every arrival (within budget)


def parse_fault_spec(spec: Optional[str]) -> list[_Fault]:
    """Parse a ``--fault-spec`` string; [] for None/empty."""
    faults: list[_Fault] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, params = part.partition(":")
        name = name.strip()
        if name not in FAULT_SITES:
            raise ValueError(
                f"unknown fault {name!r}; known: {sorted(FAULT_SITES)}")
        f = _Fault(name=name, site=FAULT_SITES[name])
        for kv in filter(None, (s.strip() for s in params.split(","))):
            if "=" not in kv:
                raise ValueError(f"fault param {kv!r} is not key=value")
            k, v = kv.split("=", 1)
            if k in ("after", "every", "seed", "times"):
                setattr(f, k, int(v))
            elif k in ("p", "ms"):
                setattr(f, k, float(v))
            elif k == "task":
                f.task = None if v == "*" else v
            elif k == "edge":
                f.edge = None if v == "*" else v
            else:
                raise ValueError(f"unknown fault param {k!r} in {part!r}")
        faults.append(f)
    return faults


class FaultInjector:
    """Deterministic injection at named sites.

    Thread-safe: counters advance under one lock (the batcher thread, heal
    threads, and recorder writers all pass through here). ``fire`` raises /
    sleeps / exits for the faults whose action is in-band, and RETURNS the
    names of triggered faults so sites with out-of-band actions
    (``step_nan``'s output corruption) can apply them.
    """

    def __init__(self, spec: Optional[str] = None):
        self.faults = parse_fault_spec(spec)
        self.spec = spec or ""
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self.faults)

    def fire(self, site: str, task: Optional[str] = None,
             edge: Optional[str] = None) -> list[str]:
        """One arrival at ``site``; applies every matching triggered fault.

        Raise order: a crash fault exits the process outright; a
        ``step_raise`` raises :class:`FaultInjected`; ``slow_step`` /
        ``net_delay`` sleep then return; out-of-band names (``step_nan``,
        ``net_drop``, ``net_dup``, ``flap_healthz``, ``kill_replica``)
        are returned to the caller to apply at the site.
        """
        fired: list[_Fault] = []
        with self._lock:
            for f in self.faults:
                if f.site != site:
                    continue
                if f.task is not None and task is not None and f.task != task:
                    continue
                if f.edge is not None and edge is not None and \
                        f.edge != edge:
                    continue
                f.count += 1
                if f.should_fire():
                    f.fired += 1
                    fired.append(f)
            # only the instances that fired sleep — matching by name would
            # charge every configured slow_step's ms when any one fires
            slow = [f.ms for f in fired
                    if f.name in ("slow_step", "net_delay")]
        triggered = [f.name for f in fired]
        for name in triggered:
            if name.startswith("crash_"):
                # simulate sudden process death: no atexit, no flush beyond
                # what the crash-safe recorder already did per row
                os._exit(_CRASH_EXIT_CODE)
        for ms in slow:
            time.sleep(ms / 1e3)
        if "step_raise" in triggered:
            raise FaultInjected(
                "injected step_raise (slab step failed after consuming "
                "donated carries)")
        if "record_eio" in triggered:
            raise OSError(5, "injected record_eio (recorder disk write "
                             "failed)")
        return triggered

    def snapshot(self) -> list[dict]:
        """Per-fault arrival/fire counts (for /stats and the matrix)."""
        with self._lock:
            return [
                {"name": f.name, "site": f.site, "count": f.count,
                 "fired": f.fired, "task": f.task}
                for f in self.faults
            ]
