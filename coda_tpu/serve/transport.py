"""Hardened replica transport: deadlines, retries, budgets, breakers.

``HttpReplica`` started life as a bare ``urlopen`` with one fixed 60 s
timeout — fine inside one container, fatal across real hosts: a slow
``/healthz`` probe deserves 2 s, an import replaying a long stream
deserves minutes, a dropped packet deserves a retry, and a replica that
has failed five calls in a row deserves to stop being called at all.
This module is the shared policy layer both replica handle types route
every verb through:

  * **per-verb deadlines** (:data:`VERB_DEADLINES`) — each verb carries
    its own timeout instead of one blanket number; overridable per
    handle.
  * **bounded retries with jittered exponential backoff** — transport
    failures (connection refused/reset, deadline expired) retry only
    when the verb is idempotent at the replica: reads always; labels
    only when they carry a ``request_id`` (the dedupe cache makes the
    replay exactly-once); ``open``/``import``/``close``/``fence`` only
    on *not-sent* failures (connection refused — the request provably
    never reached the replica). The jitter is deterministic (counter-
    addressed hash, the ``serve/faults.py`` trick) so a failure replay
    is a replay.
  * **a per-replica retry budget** — a token bucket (retries spend,
    successes slowly refill) so a black-holed replica costs a bounded
    number of extra requests, not retries-times-traffic; exhaustion
    degrades to the typed retryable :class:`ReplicaUnavailable` (a 503
    at the front door), never a hang.
  * **a per-replica circuit breaker** — trip after K *consecutive*
    transport failures, fail fast while open, allow one half-open probe
    after the cooldown (the router's health poll is the natural probe),
    close on success. Breaker state feeds the router's eviction next to
    ``/healthz``, and is reported distinctly on ``/stats``.

The **in-process handle rides the same wrapper** for parity — which is
also what makes the fleet fault matrix honest: the per-edge transport
faults (``net_drop``/``net_delay``/``net_dup``/``partition``/
``flap_healthz``, ``serve/faults.py``) fire inside :meth:`ReplicaTransport
.call`, so an in-process fleet exercises the exact retry/breaker/fencing
machinery a cross-host one would.
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable, Optional

from coda_tpu.serve.state import (
    BucketQuarantined,
    SlabFull,
    StaleOwner,
    UnknownSession,
)

#: per-verb deadlines (seconds) — the replacement for the fixed 60 s
#: blanket timeout. ``import``/``export`` budget for stream replay of a
#: long session; ``healthz`` must fail fast (it gates eviction).
VERB_DEADLINES = {
    "open": 60.0,
    "label": 60.0,
    "labels": 60.0,
    "best": 30.0,
    "trace": 60.0,
    "close": 30.0,
    "export": 120.0,
    "import": 180.0,
    "fence": 30.0,
    "stats": 30.0,
    "healthz": 5.0,
    "sessions": 60.0,
    "epoch": 10.0,
    # distributed-trace span fetch (GET /trace/id/{id}): a small read
    # the router's stitcher fans out per replica
    "trace_by_id": 10.0,
    # the prior-pool exchange rides the health cadence but moves a
    # payload (the merged pool), so it gets stats-class headroom
    "prior_sync": 30.0,
}

#: verbs that are idempotent at the replica regardless of payload: a
#: duplicate delivery (retry after a lost response) changes nothing
_IDEMPOTENT_VERBS = frozenset(
    {"best", "trace", "stats", "healthz", "sessions", "export", "epoch",
     "trace_by_id"})

#: verbs retried only when the caller proves idempotency (request_id
#: dedupe for labels); otherwise only not-sent failures retry
_GATED_VERBS = frozenset({"label", "labels"})


class ReplicaUnavailable(SlabFull):
    """Typed fast-fail: the replica's circuit is open or its retry
    budget is exhausted. Subclasses :class:`SlabFull` so the HTTP front
    door answers the same retryable 503 as every other backpressure
    signal, and the router's failover path treats it like a dead edge."""


class TransportDrop(ConnectionError):
    """An injected transport fault (net_drop / partition) ate the call —
    raised where a real lossy edge would raise ``ConnectionError``."""


def _jitter(replica_id: str, verb: str, n: int) -> float:
    """Deterministic backoff jitter in [0.5, 1.5): a counter-addressed
    hash draw (same trick as ``serve/faults.py``), so a chaos run with a
    fixed fault spec retries at reproducible instants."""
    h = hashlib.sha256(f"{replica_id}:{verb}:{n}".encode()).digest()
    return 0.5 + int.from_bytes(h[:8], "big") / float(1 << 64)


class CircuitBreaker:
    """Trip after ``threshold`` consecutive failures; half-open one probe
    after ``cooldown_s``; close on the probe's success. Locked: the
    router's verb pool and the health poller share one breaker per
    replica, and exactly-one-probe / trip-at-exactly-K are
    check-then-act sequences a race would corrupt."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0):
        import threading

        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.consecutive_failures = 0
        self.trips = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._probing or (time.monotonic() - self._opened_at
                                 >= self.cooldown_s):
                return "half_open"
            return "open"

    def allow(self) -> bool:
        """Whether a call may proceed now. In the half-open window only
        ONE caller gets through (the probe); the rest fail fast until it
        resolves."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._probing:
                return False  # a probe is in flight; everyone else waits
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if self._probing:
                # failed probe: re-open for a fresh cooldown
                self._opened_at = time.monotonic()
                self._probing = False
                self.trips += 1
            elif self._opened_at is None and \
                    self.consecutive_failures >= self.threshold:
                self._opened_at = time.monotonic()
                self.trips += 1


class RetryBudget:
    """Token bucket bounding the retry amplification one replica can
    cost: each retry spends one token, each success refunds a fraction,
    capped. An unreachable replica under heavy traffic burns the budget
    once and then fails fast instead of multiplying every request.
    Locked: take() is a read-modify-write shared across the verb pool."""

    def __init__(self, capacity: float = 16.0, refund: float = 0.1):
        import threading

        self.capacity = float(capacity)
        self.refund = float(refund)
        self.tokens = float(capacity)
        self.exhaustions = 0
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            self.exhaustions += 1
            return False

    def credit(self) -> None:
        with self._lock:
            self.tokens = min(self.capacity, self.tokens + self.refund)


class ReplicaTransport:
    """The per-replica call policy both handle types share (see module
    docstring). ``faults`` is the edge's deterministic injector (usually
    the router's, installed by ``add_replica``); ``spans`` likewise — a
    retry shows up as a ``retry/<verb>`` span nested under the router's
    ``route/<verb>`` lane, so retry cost is attributed in the same trace
    vocabulary as everything else."""

    #: exceptions that mean THE TRANSPORT failed (vs. the app answering)
    TRANSPORT_ERRORS = (ConnectionError, TimeoutError, OSError)

    def __init__(self, replica_id: str, deadlines: Optional[dict] = None,
                 max_retries: int = 2, backoff_s: float = 0.02,
                 breaker_threshold: int = 5, breaker_cooldown_s: float = 1.0,
                 retry_budget: float = 16.0, faults=None, spans=None):
        import threading

        self.replica_id = replica_id
        self.deadlines = dict(VERB_DEADLINES)
        if deadlines:
            self.deadlines.update(deadlines)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.breaker = CircuitBreaker(breaker_threshold, breaker_cooldown_s)
        self.budget = RetryBudget(retry_budget)
        self.faults = faults
        self.spans = spans
        # counters below mutate under this lock (verb pool + poller)
        self._lock = threading.Lock()
        self.calls = 0
        self.failures = 0
        self.retries_total = 0
        self.retries_by_verb: dict[str, int] = {}
        self._jitter_n = 0

    # -- policy ------------------------------------------------------------
    def deadline(self, verb: str) -> float:
        return float(self.deadlines.get(verb, 60.0))

    def _retryable(self, verb: str, err: BaseException,
                   idempotent: bool) -> bool:
        if isinstance(err, (ConnectionRefusedError, TransportDrop)):
            return True  # provably never reached the replica (the drop
            #              fault fires before the send, like a refusal)
        if verb in _IDEMPOTENT_VERBS:
            return True
        if verb in _GATED_VERBS:
            return idempotent  # request_id present -> replica dedupes
        if verb == "fence":
            return idempotent  # a drop-fence replays safely (close twice)
        return False  # open/import/close: ambiguous-outcome verbs

    # -- fault injection (the per-edge chaos sites) ------------------------
    def _fire_edge(self, verb: str):
        """One arrival at this router↔replica edge. Returns the fired
        names (``net_dup``/``flap_healthz`` are applied by the caller);
        raises :class:`TransportDrop` for drop/partition; sleeps for
        ``net_delay``."""
        if self.faults is None:
            return []
        fired = self.faults.fire("edge_call", task=verb,
                                 edge=self.replica_id)
        if verb == "healthz":
            fired += self.faults.fire("edge_healthz",
                                      edge=self.replica_id)
        if "net_drop" in fired or "partition" in fired:
            self.breaker.record_failure()
            raise TransportDrop(
                f"injected {'partition' if 'partition' in fired else 'drop'}"
                f" on edge ->{self.replica_id} ({verb})")
        return fired

    # -- the call path -----------------------------------------------------
    def call(self, verb: str, fn: Callable[[float], object],
             idempotent: bool = False):
        """Run one verb through the full policy. ``fn(deadline_s)`` does
        the actual send (an HTTP request, or the in-process method);
        app-level answers — including app-level *errors* like
        ``UnknownSession`` or the :class:`~coda_tpu.serve.state
        .StaleOwner` fencing rejection — count as transport SUCCESS (the
        edge worked; the answer is the answer)."""
        deadline = self.deadline(verb)
        attempt = 0
        while True:
            if not self.breaker.allow():
                raise ReplicaUnavailable(
                    f"replica {self.replica_id}: circuit "
                    f"{self.breaker.state} after "
                    f"{self.breaker.consecutive_failures} consecutive "
                    "transport failures")
            with self._lock:
                self.calls += 1
            try:
                fired = self._fire_edge(verb)
                if verb == "healthz" and "flap_healthz" in fired:
                    # the injected flap: the probe "answers" unready
                    # without touching the replica — the hysteresis
                    # scenario's whole point
                    self.breaker.record_success()
                    return {"ok": False, "ready": False,
                            "status": "unready", "draining": False,
                            "problems": ["flap_healthz_injected"]}
                out = fn(deadline)
                if "net_dup" in fired:
                    # duplicate delivery: the request reaches the replica
                    # twice (a retransmitted packet) — the second copy's
                    # answer is discarded, and the replica's request_id
                    # dedupe is what keeps the posterior exactly-once
                    try:
                        fn(deadline)
                    except Exception:
                        pass
                self.breaker.record_success()
                self.budget.credit()
                return out
            except (UnknownSession, StaleOwner, SlabFull,
                    BucketQuarantined, ValueError, KeyError) as e:
                # the replica ANSWERED (with an app-level error): the
                # transport is healthy — but not if we fast-failed before
                # sending (ReplicaUnavailable is transport state)
                if not isinstance(e, ReplicaUnavailable):
                    self.breaker.record_success()
                raise
            except self.TRANSPORT_ERRORS as e:
                with self._lock:
                    self.failures += 1
                if not isinstance(e, TransportDrop):
                    self.breaker.record_failure()
                if attempt >= self.max_retries or \
                        not self._retryable(verb, e, idempotent):
                    raise
                if not self.budget.take():
                    raise ReplicaUnavailable(
                        f"replica {self.replica_id}: retry budget "
                        f"exhausted retrying {verb} ({e!r})") from e
                with self._lock:
                    self.retries_total += 1
                    self.retries_by_verb[verb] = \
                        self.retries_by_verb.get(verb, 0) + 1
                    n_jit = self._jitter_n
                    self._jitter_n += 1
                delay = self.backoff_s * (2 ** attempt) * _jitter(
                    self.replica_id, verb, n_jit)
                if self.spans is not None:
                    with self.spans.span(f"retry/{verb}",
                                         lane="host:router"):
                        time.sleep(delay)
                else:
                    time.sleep(delay)
                attempt += 1

    def snapshot(self) -> dict:
        with self._lock:
            calls, failures = self.calls, self.failures
            retries = self.retries_total
            by_verb = dict(self.retries_by_verb)
        return {
            "replica": self.replica_id,
            "breaker_state": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "consecutive_failures": self.breaker.consecutive_failures,
            "calls": calls,
            "failures": failures,
            "retries_total": retries,
            "retries_by_verb": by_verb,
            "retry_budget_remaining": round(self.budget.tokens, 2),
            "retry_budget_exhaustions": self.budget.exhaustions,
        }
