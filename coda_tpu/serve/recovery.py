"""Fault-tolerant serving: checkpoint/restore, self-healing, migration.

The serving layer's state story before this module: a failed slab step
left its bucket permanently dead, and a process crash lost every live
session — even though each session already had a crash-safe per-round
JSONL stream from the flight recorder, and the replay engine already
proved those streams re-execute bitwise on the same backend. This module
closes the loop: **the posterior is the valuable state; the process (and
the slab) are disposable.**

Three capabilities, all pinned by the same bitwise replay machinery:

  * **Session checkpoint/restore** — :func:`export_session` serializes a
    session as a versioned payload: its recorder stream (the portable
    session log) plus an optional fingerprint-guarded snapshot of the
    slot's carries, host-materialized under the dispatch lock so a donated
    step can never consume them mid-read. :func:`import_session` restores
    it on any server of the same task: the snapshot fast path is accepted
    only when its posterior digest matches the stream's last recorded
    digest bitwise; otherwise (cross-fingerprint, digest drift, no
    snapshot) the session is rebuilt by replaying its oracle answers
    through the bucket's precompiled step, every replayed round verified
    bitwise against the stream. The restored session keeps its id — the
    client's handle survives the migration. This is the single-host
    prerequisite for the ROADMAP's replica migration.
  * **Bucket self-healing** — :func:`heal_bucket` rebuilds a quarantined
    slab (a step failure consumed the donated carries) by replaying every
    live slot's stream into a freshly allocated slab, one dispatch per
    round for ALL slots (warm-pool executables make this fast), verifying
    each replayed round — including the P(best) digest — bitwise.
    :class:`BucketHealer` runs it off the batcher thread with bounded
    retries and exponential backoff; only a digest mismatch or exhausted
    retries degrade to the old terminal state.
  * **Crash restore** — :func:`restore_app_sessions` scans a
    ``--record-dir`` for streams without a close marker and re-imports
    each one, so a SIGKILLed server restarted against the same directory
    resumes every live session, replay-verified.

``replay_serve_main`` (``python -m coda_tpu.cli replay-serve <dir>``) is
the offline face: verify any session stream against a fresh slab without
a server, the way ``cli replay`` verifies batch records.
"""

from __future__ import annotations

import base64
import json
import os
import re
import threading
import time
from typing import Optional

import numpy as np

from coda_tpu.serve.state import BucketQuarantined, SelectorSpec

#: bump on any change to the export payload's fields
SESSION_EXPORT_VERSION = 1

# the only session ids this package ever mints (uuid4 hex): imports must
# match, both because the HTTP routes can address nothing else and because
# the id lands in a recorder file path (session_<id>.jsonl)
_SID_RE = re.compile(r"^[0-9a-f]{1,64}$")

# result-row quantities a replayed round must reproduce bitwise
_INT_QUANTITIES = ("next_idx", "best")
_FLOAT_QUANTITIES = ("next_prob", "pbest_max", "pbest_entropy")


class ReplayMismatch(RuntimeError):
    """A replayed round diverged bitwise from its recorded row."""


class ImportRejected(ValueError):
    """The import payload cannot be restored here (wrong task/method/data,
    or its stream failed replay verification)."""


def _counter(name: str, help: str = ""):
    from coda_tpu.telemetry import get_registry

    return get_registry().counter(name, help)


def _schema_version() -> int:
    from coda_tpu.telemetry.recorder import SESSION_SCHEMA_VERSION

    return SESSION_SCHEMA_VERSION


def _stream_version_error(meta: dict) -> Optional[str]:
    """The schema-gate verdict for one session stream's meta, or None
    when it is replayable by this build: the current version always; the
    previous (pre-batching) version too, whose rows are a strict subset
    at acq_batch=1 — rejecting it would discard every in-flight session
    across a deploy. A v2 stream's missing ``acq_batch`` reads as 1; the
    q-mismatch against a batch server is caught by the acq_batch check,
    not mislabeled a schema problem."""
    from coda_tpu.telemetry.recorder import SUPPORTED_SESSION_VERSIONS

    v = meta.get("v")
    if v is not None and v not in SUPPORTED_SESSION_VERSIONS:
        return (f"stream schema v{v}; this build replays "
                f"v{list(SUPPORTED_SESSION_VERSIONS)}")
    return None


# ---------------------------------------------------------------------------
# array <-> JSON-safe codec for snapshot carries
# ---------------------------------------------------------------------------

def _pack(arr) -> dict:
    a = np.asarray(arr)
    # ascontiguousarray PROMOTES 0-d arrays to (1,); reshape back so a
    # scalar state leaf (the surrogate fit's counters) round-trips with
    # its rank intact — the import-side structural guard compares shapes
    a = np.ascontiguousarray(a).reshape(a.shape)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _unpack(d: dict) -> np.ndarray:
    data = d["data"]
    if isinstance(data, str):
        data = base64.b64decode(data)
    # raw bytes pass through untouched — the spill store's lazy frames
    # (serve/spill.py) hand the decompressed leaf bytes over directly,
    # skipping the base64 round trip the JSON transport needs
    return np.frombuffer(
        data, dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


# ---------------------------------------------------------------------------
# bitwise row verification (the restore/heal contract)
# ---------------------------------------------------------------------------

def _f32_bits_equal(a, b) -> bool:
    return (np.float32(a).tobytes() == np.float32(b).tobytes())


def check_row(recorded: dict, replayed: dict, round_i: int,
              sid: str = "?") -> None:
    """Raise :class:`ReplayMismatch` naming the first diverging quantity.

    Integers compare exact; floats compare BITWISE (same-backend replay
    through the identical compiled step admits nothing less — and a NaN
    poisoned into the recorded stream can never silently verify against a
    finite replay)."""
    def _as_list(v):
        return list(v) if isinstance(v, (list, tuple)) else [v]

    for q in _INT_QUANTITIES:
        # batch-label rows carry q-wide lists (next_idx of a q>1 bucket);
        # compare element-exact either way
        rec_l, rep_l = _as_list(recorded[q]), _as_list(replayed[q])
        if len(rec_l) != len(rep_l) or any(
                int(x) != int(y) for x, y in zip(rec_l, rep_l)):
            raise ReplayMismatch(
                f"session {sid} round {round_i}: {q} recorded "
                f"{recorded[q]} != replayed {replayed[q]}")
    for q in _FLOAT_QUANTITIES:
        rec = recorded.get(q)
        rep = replayed.get(q)
        if rec is None and rep is None:
            continue  # method exposes no posterior digest
        if (rec is None) != (rep is None):
            raise ReplayMismatch(
                f"session {sid} round {round_i}: {q} present on only one "
                f"side (recorded {rec!r}, replayed {rep!r})")
        rec_l, rep_l = _as_list(rec), _as_list(rep)
        if len(rec_l) != len(rep_l) or any(
                not _f32_bits_equal(x, y) for x, y in zip(rec_l, rep_l)):
            raise ReplayMismatch(
                f"session {sid} round {round_i}: {q} recorded {rec!r} != "
                f"replayed {rep!r} (bitwise)")


def data_rows(rows) -> list:
    """The decision rows of a stream (meta/close marker lines dropped)."""
    return [r for r in (rows or []) if not r.get("kind")]


def last_digest(rows) -> Optional[tuple]:
    """The last recorded (pbest_max, pbest_entropy) of a stream, or None
    when the stream is empty or the method records no posterior digest."""
    rows = data_rows(rows)
    if not rows or rows[-1].get("pbest_max") is None:
        return None
    return (rows[-1]["pbest_max"], rows[-1].get("pbest_entropy"))


def _row_label_count(row: dict) -> int:
    """Oracle answers a stream row committed: q for a batch-label row
    (list-valued ``label``), else 1."""
    if not row.get("do_update"):
        return 0
    lab = row.get("label")
    return len(lab) if isinstance(lab, (list, tuple)) else 1


def _request_from_row(row: dict) -> dict:
    if row.get("do_update"):
        lab = row["label"]
        if isinstance(lab, (list, tuple)):
            # batch-label row (acq_batch > 1): the whole q-wide answer
            # set replays through one dispatch, like it was applied
            return {"do_update": True,
                    "idx": [int(v) for v in row["labeled_idx"]],
                    "label": [int(v) for v in lab],
                    "prob": [float(v) for v in row["prob"]]}
        return {"do_update": True, "idx": int(row["labeled_idx"]),
                "label": int(lab), "prob": float(row["prob"])}
    return {"do_update": False}


def replay_live_coalesced(bucket, live, *, dispatch, alive=None,
                          on_fail=None) -> int:
    """Drive many slots' recorded rows through ``bucket`` with ONE masked
    dispatch serving every live slot per round — the shared choreography
    of :func:`heal_bucket` and :func:`restore_app_sessions` (a serial
    per-session replay would run capacity-times more full-slab steps).

    ``live`` maps ``slot -> (sid, rows)`` and is MUTATED: a slot whose
    session dies mid-replay (``alive``) or fails is removed, so the caller
    reads the survivors out of it. ``dispatch(reqs)`` runs one coalesced
    round (the caller owns locking/flags). Without ``on_fail`` any failure
    raises — the heal contract, where one divergence invalidates the whole
    rebuild. With ``on_fail(sid, err)``, a :class:`ReplayMismatch` drops
    only that slot, and a dispatch-level error drops every slot in the
    round's request set then stops — the restore contract, where one
    corrupt stream must not brick the others. Returns the number of
    replayed rounds."""
    n = 0
    max_rounds = max((len(r) for _, r in live.values()), default=0)
    for k in range(max_rounds):
        reqs = {}
        for slot, (sid, rows) in list(live.items()):
            if k >= len(rows):
                continue
            if alive is not None and not alive(sid):
                # closed by its client mid-replay (close/release are
                # lock-free): a finished session needs no rebuild — its
                # slot's rows stay garbage until reallocation, like any
                # released slot
                del live[slot]
                continue
            reqs[slot] = _request_from_row(rows[k])
        if not reqs:
            break
        try:
            res = dispatch(reqs)
        except BaseException as e:
            if on_fail is None:
                raise
            # the bucket itself is down (e.g. the step consumed its
            # donated carries): every session still rebuilding here fails
            # attributably; the caller's heal hook takes over
            for slot in list(reqs):
                sid, _ = live.pop(slot)
                on_fail(sid, e)
            break
        for slot in reqs:
            sid, rows = live[slot]
            try:
                check_row(rows[k], res[slot], k, sid=sid)
            except ReplayMismatch as e:
                if on_fail is None:
                    raise
                del live[slot]
                on_fail(sid, e)
        n = k + 1
    return n


def replay_rows_into_slot(bucket, slot: int, rows, sid: str = "?",
                          verify: bool = True) -> Optional[dict]:
    """Re-drive a session's recorded rows through the bucket's compiled
    step into ``slot`` (freshly staged with the session's init — see
    ``Bucket.stage_fresh``), one dispatch per row, verifying each round
    bitwise. Returns the last replayed result row."""
    last = None
    for k, row in enumerate(data_rows(rows)):
        with bucket.lock:
            res = bucket.dispatch({slot: _request_from_row(row)})[slot]
        if verify:
            check_row(row, res, k, sid=sid)
        last = res
    return last


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def snapshot_fingerprint(bucket) -> dict:
    """The axes along which a carries snapshot is bit-portable: same
    backend + jax version + selector config + padded shape + step
    lowering. Anything else restores via the replay path instead."""
    import jax

    return {
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "method": bucket.spec.method,
        "spec_kwargs": [list(kv) for kv in bucket.spec.kwargs],
        "acq_batch": bucket.acq_batch,
        "shape": list(bucket.shape),
        "n_valid": bucket.n_valid,
        "step_impl": bucket.step_impl,
    }


def build_export_payload(app, sess, snapshot=None) -> dict:
    """The serialization shared by ``POST /session/{id}/export`` and the
    warm-tier demotion (serve/tiering.py — a demoted session IS its export
    payload, minus the HTTP hop). Caller has resolved ``sess`` and
    guaranteed it stays resident for the duration (a pin, or the export
    verb's own lookup). ``snapshot`` injects a pre-taken
    ``(leaves, key)`` (the sweeper's batched ``snapshot_slots``) instead
    of reading the slab here."""
    bucket = sess.bucket
    payload = {
        "v": SESSION_EXPORT_VERSION,
        "kind": "session_export",
        "session": sess.sid,
        "task": sess.task,
        "method": bucket.spec.method,
        "spec_kwargs": [list(kv) for kv in bucket.spec.kwargs],
        "acq_batch": bucket.acq_batch,
        "seed": sess.seed,
        # ownership epoch: preserved verbatim by demote/wake round trips;
        # only the router's migration commit bumps it (fencing)
        "epoch": sess.epoch,
        "dataset": {k: app.store.task_meta(sess.task).get(k)
                    for k in ("shape", "digest")},
        "fingerprint": snapshot_fingerprint(bucket),
        "carries": None,
        "key": None,
    }
    if sess.prior_fit is not None:
        # the applied-prior record rides the payload so the import-side
        # REPLAY fallback (and any later heal on the destination) seeds
        # the same init this session was admitted with
        payload["prior_fit"] = dict(sess.prior_fit)
    if sess.prior_contributed:
        # the once-flag rides too: a demoted session contributes at
        # demotion, and a later wake+close must not fold it in twice
        payload["prior_contributed"] = True
    # snapshot FIRST (host-materialized under the bucket lock — see
    # Bucket.snapshot_slot for the donation race), stream second: if a
    # dispatch lands between the two, the stream is ahead of the snapshot,
    # the import-side digest check fails, and restore falls back to the
    # replay path — never a torn state
    try:
        leaves, key = (snapshot if snapshot is not None
                       else bucket.snapshot_slot(sess.slot))
        payload["carries"] = [_pack(x) for x in leaves]
        payload["key"] = _pack(key)
    except (BucketQuarantined, RuntimeError):
        pass  # slab lost: the stream-only export is still complete
    rows = data_rows(app.recorder.history(sess.sid))
    payload["rows"] = rows
    payload["n_labeled"] = sum(_row_label_count(r) for r in rows)
    payload["last"] = dict(rows[-1]) if rows else None
    # parked per-slot crowd answers of the CURRENT round (the async
    # answer verb): they ride the payload so a migration loses none
    with app.store.lock:
        if sess.parked:
            payload["parked"] = {str(j): dict(e)
                                 for j, e in sess.parked.items()}
    return payload


def export_session(app, sid: str) -> dict:
    """Serialize one live session as a self-contained, versioned payload.

    Always carries the recorder stream (the portable, replayable session
    log — ``n_labeled``/``last`` are derived from it, the single source of
    truth). When the slab is readable, also a fingerprint-guarded snapshot
    of the slot's carries for the import fast path; a quarantined bucket
    exports stream-only (the stream IS the session). Leaves the session
    live — the drain flow closes it separately once the peer confirms the
    import."""
    sess = app.store.get(sid)
    if sess.restoring:
        # mid-restore the slot and the recorder history are half-built;
        # an export now would serialize an empty stream as the session
        raise BucketQuarantined(
            f"session {sid} is being restored; retry shortly")
    payload = build_export_payload(app, sess)
    app.metrics.record_recovery("exported")
    _counter("serve_sessions_exported_total",
             "Sessions serialized for checkpoint/migration").inc()
    return payload


def export_all(app) -> list[dict]:
    """Export every open session — resident AND parked (the drain/migrate
    sweep; a rolling restart must carry all three tiers). A session closed
    by its client between the listing and its export is skipped — a
    finished session needs no migration."""
    from coda_tpu.serve.state import UnknownSession

    with app.store.lock:
        sids = list(app.store._sessions)
    out = []
    for sid in sids:
        try:
            out.append(export_session(app, sid))
        except UnknownSession:
            pass
    tiers = getattr(app, "tiers", None)
    if tiers is not None:
        seen = {p["session"] for p in out}
        out += [p for p in tiers.export_parked()
                if p["session"] not in seen]
    return out


# ---------------------------------------------------------------------------
# import / restore
# ---------------------------------------------------------------------------

def _fingerprint_compatible(fp: dict, bucket) -> bool:
    return fp == snapshot_fingerprint(bucket)


def _close_quietly(store, sid: str) -> None:
    """Cleanup close on an error path: a racing client DELETE may have
    already popped the sid — that must not mask the original error."""
    try:
        store.close(sid)
    except Exception:
        pass


def _finalize_restored(sess, rows) -> None:
    """Rebuild a restored session's host bookkeeping from its rows:
    label count, last result row, and the idempotency cache — a label the
    client retries across the migration must dedupe on the new server."""
    sess.n_labeled = sum(_row_label_count(r) for r in rows)
    sess.last = dict(rows[-1]) if rows else {}
    for row in rows:
        rid = row.get("request_id")
        if rid:
            sess.recent[rid] = {
                k: row.get(k) for k in ("next_idx", "next_prob",
                                        "best", "stochastic",
                                        "pbest_max", "pbest_entropy")}


def _repark_answers(app, sess, parked) -> None:
    """Re-park a restored session's pending per-slot crowd answers (the
    async answer verb) and re-stream their park rows — the restored
    stream is rewritten from data rows only, and a crash after THIS
    restore must find the parks again (0 lost answers, the robustness
    artifact's bound)."""
    if not parked:
        return
    q = sess.bucket.acq_batch
    round_idx = sess.n_labeled // q
    entries = {}
    for j, e in parked.items():
        j = int(j)
        if 0 <= j < q:
            entries[j] = {"label": int(e["label"]),
                          "request_id": e.get("request_id"),
                          "seq": int(e.get("seq", 0))}
    with app.store.lock:
        sess.parked = entries
        sess.park_seq = 1 + max((e["seq"] for e in entries.values()),
                                default=-1)
    for j in sorted(entries):
        e = entries[j]
        app.recorder.append(sess.sid, {
            "kind": "answer_park", "session": sess.sid,
            "round": round_idx, "slot": j, "label": e["label"],
            "request_id": e.get("request_id"), "seq": e["seq"]})


def parked_from_rows(raw_rows, n_rounds: int) -> dict:
    """The pending per-slot answers of a raw stream: ``answer_park`` rows
    addressed to the CURRENT round (``round == n_rounds`` — parks of
    completed rounds are superseded by their data row). Later rows win a
    slot (re-park after a failed drain)."""
    parked = {}
    for r in (raw_rows or []):
        if r.get("kind") != "answer_park":
            continue
        if int(r.get("round", -1)) != n_rounds:
            continue
        parked[int(r["slot"])] = {"label": r.get("label"),
                                  "request_id": r.get("request_id"),
                                  "seq": int(r.get("seq", 0))}
    return parked


def import_session(app, payload: dict, count: bool = True) -> dict:
    """Restore an exported session into this server; returns
    ``{restored_via, session, n_labeled, rounds}``.

    Restore order: (1) snapshot fast path — carries present AND
    fingerprint matches this bucket AND the standalone posterior digest of
    the written slot equals the stream's last recorded digest bitwise;
    (2) replay path — re-drive the stream through the bucket's compiled
    step from the session's init, every round verified bitwise. A session
    that fails both is rejected whole (attributable), never half-admitted.

    ``count=False`` skips the open/imported metrics — the tier wake path
    (serve/tiering.py) restores through here but counts its own events
    (a wake is a page-in, not a new session).
    """
    if payload.get("v") != SESSION_EXPORT_VERSION:
        raise ImportRejected(
            f"export payload v={payload.get('v')!r}; this build imports "
            f"v{SESSION_EXPORT_VERSION}")
    task = payload["task"]
    if task not in app.store.tasks():
        raise ImportRejected(f"task {task!r} is not registered here")
    meta = app.store.task_meta(task)
    want_ds = payload.get("dataset") or {}
    if want_ds.get("digest") and want_ds["digest"] != meta.get("digest"):
        raise ImportRejected(
            f"dataset digest mismatch for task {task!r}: session was "
            f"served on {want_ds['digest']}, this server has "
            f"{meta.get('digest')} — restoring against different data "
            "answers a different question")
    if payload["method"] != app.spec.method or \
            [list(kv) for kv in app.spec.kwargs] != payload["spec_kwargs"]:
        raise ImportRejected(
            f"selector config mismatch: session ran "
            f"{payload['method']}{payload['spec_kwargs']}, this server "
            f"serves {app.spec.method}{[list(k) for k in app.spec.kwargs]}")
    want_q = int(payload.get("acq_batch", 1))
    if want_q != app.spec.acq_batch:
        # a q-mismatched import would replay q-wide rows through a
        # differently-shaped compiled step — reject with the real reason
        raise ImportRejected(
            f"acq_batch mismatch: session batches {want_q} label(s) per "
            f"round, this server serves acq_batch={app.spec.acq_batch}")
    sid = payload.get("session")
    if not isinstance(sid, str) or not _SID_RE.match(sid):
        # an unchecked id would flow into a recorder file path AND create
        # a session the hex-only HTTP routes can never address again
        raise ImportRejected(
            f"invalid session id {sid!r}: expected the lowercase-hex id "
            "the export was taken under")
    rows = data_rows(payload.get("rows"))
    # published gated: the sid is addressable from here (the client's
    # handle must resolve), but labels answer retryable 503 until the
    # posterior AND the request_id dedupe cache are rebuilt — a retry
    # landing mid-restore must neither 404 nor double-apply
    # the imported copy's prior is the RECORDED one (payload), never the
    # pool's current state — replay must reproduce the admitted init
    sess = app.store.open(task, app.spec, seed=int(payload["seed"]),
                          sid=sid, restoring=True,
                          prior=payload.get("prior_fit"))
    # the copy's ownership epoch is the payload's — set before the verbs
    # unblock so a fenced verb can never race an un-epoched window
    sess.epoch = int(payload.get("epoch") or 0)
    sess.prior_contributed = bool(payload.get("prior_contributed"))
    bucket = sess.bucket
    try:
        restored_via = None
        if payload.get("carries") is not None and _fingerprint_compatible(
                payload.get("fingerprint") or {}, bucket):
            # verify FIRST, on the imported host leaves — no slab access,
            # no bucket lock, so a wake/import never waits out an
            # in-flight dispatch just to check a payload — then stage the
            # slot write only for a payload that verified
            leaves = [_unpack(d) for d in payload["carries"]]
            want = last_digest(rows)
            if want is not None:
                got = bucket.digest_leaves(leaves)
                if got is not None and \
                        _f32_bits_equal(got[0], want[0]) and \
                        _f32_bits_equal(got[1], want[1]):
                    bucket.restore_slot(sess.slot, leaves,
                                        _unpack(payload["key"]))
                    restored_via = "snapshot"
            # no digest on either side -> the snapshot is UNVERIFIABLE;
            # fall through to the replay path, which verifies every round
        if restored_via is None:
            bucket.stage_fresh(sess.slot, sess.seed, prior=sess.prior_fit)
            replay_rows_into_slot(bucket, sess.slot, rows, sid=sess.sid)
            restored_via = "replay"
        _finalize_restored(sess, rows)
        app.recorder.import_history(
            sess.sid, meta={"task": task, "method": payload["method"],
                            "spec_kwargs": payload["spec_kwargs"],
                            "acq_batch": want_q,
                            "seed": sess.seed,
                            "epoch": sess.epoch,
                            "shape": meta.get("shape"),
                            "digest": meta.get("digest"),
                            **({"surrogate_prior": dict(sess.prior_fit)}
                               if sess.prior_fit is not None else {}),
                            "imported_via": restored_via},
            rows=rows)
        # pending async crowd answers ride the payload; import_history
        # rewrote the stream from data rows only, so re-stream the parks
        _repark_answers(app, sess, payload.get("parked") or {})
    except ReplayMismatch as e:
        _close_quietly(app.store, sess.sid)
        raise ImportRejected(f"stream failed replay verification: {e}")
    except BaseException:
        _close_quietly(app.store, sess.sid)
        raise
    sess.restoring = False  # fully rebuilt: labels flow again
    if count:
        app.metrics.record_session("open")  # pairs with close's 'close'
        app.metrics.record_recovery("imported")
        _counter("serve_sessions_imported_total",
                 "Sessions restored from checkpoint/migration "
                 "payloads").inc()
    return {"restored_via": restored_via, "session": sess.sid,
            "n_labeled": sess.n_labeled, "rounds": len(rows)}


# ---------------------------------------------------------------------------
# crash restore: rebuild live sessions from a --record-dir
# ---------------------------------------------------------------------------

def load_session_stream(path: str):
    """``(meta, rows, closed)`` from one ``session_<id>.jsonl``.

    Crash-tolerant: a process killed mid-write leaves at most one
    truncated FINAL line, which is dropped; a torn line anywhere else is
    real corruption and raises."""
    meta: dict = {}
    rows: list = []
    closed = False
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                break  # torn final line: the crash the recorder flushes for
            raise
        kind = row.get("kind")
        if kind == "session_meta":
            meta = row
        elif kind == "session_close":
            closed = True
        else:
            rows.append(row)
            # a data row AFTER a close marker means the stream was resumed
            # (same-dir migration): the session is live again
            closed = False
    return meta, rows, closed


def iter_session_streams(record_dir: str):
    """Yield ``(sid, path)`` for every session stream in a record dir."""
    for fn in sorted(os.listdir(record_dir)):
        if fn.startswith("session_") and fn.endswith(".jsonl"):
            yield fn[len("session_"):-len(".jsonl")], \
                os.path.join(record_dir, fn)


def restore_app_sessions(app, record_dir: Optional[str] = None) -> dict:
    """Restore every un-closed session stream found in ``record_dir``
    (default: the app's own recorder directory) — the crash-restart path.

    Each wave: every restorable stream is first admitted GATED
    (``Session.restoring`` — the sid resolves, labels answer retryable
    503), then all sessions sharing a bucket are replayed COALESCED —
    one masked slab dispatch serves every restoring slot per round, the
    same choreography :func:`heal_bucket` uses. A serial
    per-session replay would run ``capacity`` times more full-slab steps
    at exactly the moment (crash under full load) this path exists for.

    With tiering enabled (``app.tiers``), MORE streams than slab capacity
    restore in waves: each restored wave is demoted to the warm tier
    before the next wave admits, so a crash of a beyond-capacity server
    restarts with its whole open-session population intact (hot set
    re-forms on demand via wake-on-label). Hibernated sessions carry a
    close marker and are correctly skipped — their spill files are the
    authority and the TierManager re-indexes them at startup.

    Per-session failures are collected, not raised: one corrupt stream
    must not brick the whole restart. Returns
    ``{restored: [sid], skipped_closed: n, failed: {sid: reason}}``."""
    d = record_dir or app.recorder.out_dir
    report = {"restored": [], "skipped_closed": 0, "failed": {}}
    if not d or not os.path.isdir(d):
        return report
    # phase 1: validate every stream (no admission yet)
    pending: list = []         # (sid, meta, rows)
    for sid, path in iter_session_streams(d):
        try:
            meta, rows, closed = load_session_stream(path)
        except Exception as e:
            report["failed"][sid] = f"unreadable stream: {e}"
            continue
        v_err = _stream_version_error(meta)
        if v_err is not None:
            # an unsupported stream version would replay with missing/
            # mis-shaped fields and misreport them as divergence — name
            # the real incompatibility instead
            report["failed"][sid] = v_err
            continue
        if closed:
            report["skipped_closed"] += 1
            continue
        if app.store.alive(sid):
            continue  # already live (e.g. double restore call)
        if not _SID_RE.match(sid):
            report["failed"][sid] = f"invalid session id {sid!r} in stream"
            continue
        raw_rows, rows = rows, data_rows(rows)
        # pending async crowd answers live in answer_park kind-rows of the
        # CURRENT round; rebuild them after replay so a crash between an
        # answer arriving and its round completing loses nothing
        n_rounds = (sum(_row_label_count(r) for r in rows)
                    // max(1, int(meta.get("acq_batch", 1))))
        parked = parked_from_rows(raw_rows, n_rounds)
        task = meta.get("task")
        try:
            if task not in app.store.tasks():
                raise ImportRejected(f"task {task!r} is not registered "
                                     "here")
            want_dg = meta.get("digest")
            have_dg = app.store.task_meta(task).get("digest")
            if want_dg and want_dg != have_dg:
                raise ImportRejected(
                    f"dataset digest mismatch for task {task!r}: stream "
                    f"was recorded on {want_dg}, this server has {have_dg}")
            if meta.get("method") and meta["method"] != app.spec.method:
                raise ImportRejected(
                    f"selector config mismatch: stream ran "
                    f"{meta['method']}, this server serves "
                    f"{app.spec.method}")
            want_kw = meta.get("spec_kwargs")
            have_kw = [list(kv) for kv in app.spec.kwargs]
            if want_kw is not None and [list(kv) for kv in want_kw] \
                    != have_kw:
                # without this, a kwargs-mismatched restart surfaces as
                # a per-round "bitwise divergence" instead of the named
                # config error (import_session already checks both)
                raise ImportRejected(
                    f"selector config mismatch: stream ran "
                    f"{meta['method']}{want_kw}, this server serves "
                    f"{app.spec.method}{have_kw}")
            # a v2 (pre-batching) stream carries no acq_batch: it is an
            # acq_batch=1 stream by construction
            want_q = int(meta.get("acq_batch", 1))
            if want_q != app.spec.acq_batch:
                raise ImportRejected(
                    f"acq_batch mismatch: stream batches {want_q} "
                    f"label(s) per round, this server serves "
                    f"acq_batch={app.spec.acq_batch}")
        except Exception as e:
            report["failed"][sid] = repr(e)
            continue
        pending.append((sid, meta, rows, parked))
    # phase 2: admit + replay in slab-sized waves (one wave = the whole
    # set when everything fits; beyond-capacity restarts need app.tiers)
    tiers = getattr(app, "tiers", None)
    wave_size = max(1, int(app.store.capacity))
    while pending:
        wave, pending = pending[:wave_size], pending[wave_size:]
        staged: list = []      # (sess, rows, meta, parked)
        for sid, meta, rows, parked in wave:
            try:
                # the stream meta's applied-prior record (if the session
                # was admitted prior-seeded) re-applies here — the pool
                # may have moved on, this session's history has not
                sess = app.store.open(meta.get("task"), app.spec,
                                      seed=int(meta.get("seed", 0)),
                                      sid=sid, restoring=True,
                                      prior=meta.get("surrogate_prior"))
                # a crash-restored copy keeps its stream's ownership
                # epoch: if the session had migrated away and this stream
                # was never fenced (the crash window), the restored copy
                # is STALE and the epoch makes the fence still hold
                sess.epoch = int(meta.get("epoch") or 0)
                sess.bucket.stage_fresh(sess.slot, sess.seed,
                                        prior=sess.prior_fit)
            except Exception as e:
                report["failed"][sid] = repr(e)
                continue
            staged.append((sess, rows, meta, parked))
        # coalesced bitwise-verified replay, one dispatch per round per
        # bucket; a diverging stream fails ONLY its session
        by_bucket: dict = {}
        for sess, rows, meta, parked in staged:
            by_bucket.setdefault(
                id(sess.bucket), (sess.bucket, []))[1].append(
                    (sess, rows, meta, parked))
        for bucket, items in by_bucket.values():
            live = {sess.slot: (sess.sid, rows)
                    for sess, rows, _, _ in items}

            def locked_dispatch(reqs, _bucket=bucket):
                with _bucket.lock:
                    return _bucket.dispatch(reqs)

            def on_fail(sid, e):
                if isinstance(e, ReplayMismatch):
                    report["failed"][sid] = repr(ImportRejected(
                        f"stream failed replay verification: {e}"))
                else:
                    report["failed"][sid] = f"restore dispatch failed: {e!r}"
                _close_quietly(app.store, sid)

            # per-session isolation: a diverging stream fails ONLY its
            # session (restoring sessions are close-gated, so no `alive`
            # check needed)
            replay_live_coalesced(bucket, live, dispatch=locked_dispatch,
                                  on_fail=on_fail)
            for sess, rows, meta, parked in items:
                if sess.slot not in live:
                    continue
                _finalize_restored(sess, rows)
                app.recorder.import_history(
                    sess.sid, meta={"task": sess.task,
                                    "method": meta.get("method")
                                    or app.spec.method,
                                    "spec_kwargs": meta.get("spec_kwargs")
                                    or [list(kv) for kv in app.spec.kwargs],
                                    "acq_batch": app.spec.acq_batch,
                                    "seed": sess.seed,
                                    "epoch": sess.epoch,
                                    "shape": meta.get("shape"),
                                    "digest": meta.get("digest"),
                                    **({"surrogate_prior":
                                        dict(sess.prior_fit)}
                                       if sess.prior_fit is not None
                                       else {}),
                                    "imported_via": "replay"},
                    rows=rows)
                _repark_answers(app, sess, parked)
                sess.restoring = False
                report["restored"].append(sess.sid)
                app.metrics.record_session("open")
                app.metrics.record_recovery("restored")
                _counter("serve_sessions_restored_total",
                         "Sessions rebuilt from their JSONL streams after "
                         "a crash").inc()
        if pending and tiers is not None:
            # make room for the next wave: page this one out to warm (it
            # just replayed, so its payload is a verified snapshot); the
            # hot set re-forms on demand via wake-on-label. Batched per
            # bucket — one slab snapshot demotes the whole wave — and
            # unstarted sessions (a stream with zero data rows) demote
            # too, or their slots would starve every later wave.
            demote_by_bucket: dict = {}
            for sess, rows, meta, parked in staged:
                if app.store.alive(sess.sid):
                    demote_by_bucket.setdefault(
                        id(sess.bucket), (sess.bucket, []))[1].append(
                            sess.sid)
            for bucket, wave_sids in demote_by_bucket.values():
                tiers.demote_batch(bucket, wave_sids,
                                   allow_unstarted=True)
    return report


# ---------------------------------------------------------------------------
# bucket self-healing
# ---------------------------------------------------------------------------

def heal_bucket(bucket, store, recorder) -> dict:
    """Rebuild a quarantined bucket's slab from its sessions' streams.

    Under the bucket lock: allocate a fresh slab, re-stage every live
    slot's init, then replay round-by-round — ONE dispatch serves every
    rebuilding slot per round (the same coalescing the serving path uses),
    with each slot's replayed row verified bitwise against its stream,
    posterior digest included. On full verification the quarantine lifts;
    a mismatch raises :class:`ReplayMismatch` (the caller degrades the
    bucket to terminal — a rebuild that cannot be verified must never
    silently re-admit)."""
    t0 = time.perf_counter()
    sessions = store.sessions_on(bucket)
    live = {
        s.slot: (s.sid, data_rows(recorder.history(s.sid)) or [])
        for s in sessions
    }
    with bucket.lock:
        # the quarantine flag stays SET for the whole rebuild: allocate()
        # never takes this lock (staged admission), so lifting the flag
        # early would let a concurrent open stage a write that our own
        # dispatches below apply into a slot mid-rebuild. Admissions stay
        # 503-refused until the rebuild is verified; our dispatches go
        # through the `_healing` override.
        bucket.reset_slab()
        for s in sessions:
            # re-apply each session's RECORDED admission prior: a heal
            # replays from the admitted init, and a prior-seeded session
            # healed cold would diverge bitwise on its first verify row
            bucket.stage_fresh(s.slot, s.seed, prior=s.prior_fit)
        # no on_fail: one divergence invalidates the WHOLE rebuild (the
        # caller degrades the bucket to terminal)
        n_replayed = replay_live_coalesced(
            bucket, live,
            dispatch=lambda reqs: bucket.dispatch(reqs, _healing=True),
            alive=store.alive)
        bucket.heals += 1
        bucket.quarantined = None
    return {"sessions": len(sessions), "rounds": n_replayed,
            "seconds": time.perf_counter() - t0}


class BucketHealer:
    """Runs :func:`heal_bucket` off the batcher thread when a dispatch
    quarantines a bucket, with bounded retries and exponential backoff.

    One heal thread per bucket at a time; a digest mismatch degrades the
    bucket to terminal immediately (an unverifiable rebuild must not
    serve), exhausted retries likewise — everything else re-admits. A
    bucket that keeps getting re-quarantined is capped at ``max_heals``
    lifetime rebuilds before degrading (a persistently failing step is a
    bug, not weather)."""

    def __init__(self, store, recorder, metrics=None, max_attempts: int = 3,
                 backoff_s: float = 0.05, max_heals: int = 8):
        self.store = store
        self.recorder = recorder
        self.metrics = metrics
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.max_heals = int(max_heals)
        self._lock = threading.Lock()
        self._inflight: set = set()
        self.last_report: dict = {}

    def schedule(self, bucket, error: Optional[BaseException] = None,
                 sync: bool = False) -> bool:
        """Kick off a heal for ``bucket`` (idempotent while one is in
        flight). ``sync`` heals in the calling thread — the test hook."""
        with self._lock:
            if bucket.failed is not None or id(bucket) in self._inflight:
                return False
            if bucket.heals >= self.max_heals:
                bucket.failed = (
                    f"bucket exceeded {self.max_heals} slab rebuilds — "
                    f"persistent step failure (last: {error!r})")
                bucket.quarantined = None
                self._fail_metrics()  # terminal degradation must count
                return False          # like every other one
            self._inflight.add(id(bucket))
        _counter("serve_buckets_quarantined_total",
                 "Buckets quarantined by a step failure that consumed "
                 "donated carries").inc()
        if self.metrics is not None:
            self.metrics.record_recovery("quarantined")
        if sync:
            self._run(bucket)
            return True
        threading.Thread(target=self._run, args=(bucket,),
                         name=f"serve-heal-{bucket.task}",
                         daemon=True).start()
        return True

    def _run(self, bucket) -> None:
        try:
            last_err: Optional[BaseException] = None
            for attempt in range(self.max_attempts):
                try:
                    info = heal_bucket(bucket, self.store, self.recorder)
                except ReplayMismatch as e:
                    bucket.failed = (f"slab rebuild failed digest "
                                     f"verification: {e}")
                    bucket.quarantined = None
                    self._fail_metrics()
                    return
                except BaseException as e:
                    last_err = e
                    time.sleep(self.backoff_s * (2 ** attempt))
                    continue
                self.last_report = info
                _counter("serve_buckets_healed_total",
                         "Quarantined buckets rebuilt from session "
                         "streams and digest-verified").inc()
                if self.metrics is not None:
                    self.metrics.record_recovery("healed")
                return
            bucket.failed = (f"slab rebuild failed after "
                             f"{self.max_attempts} attempts: {last_err!r}")
            bucket.quarantined = None
            self._fail_metrics()
        finally:
            with self._lock:
                self._inflight.discard(id(bucket))

    def _fail_metrics(self) -> None:
        _counter("serve_heal_failures_total",
                 "Bucket rebuilds degraded to terminal (digest mismatch "
                 "or exhausted retries)").inc()
        if self.metrics is not None:
            self.metrics.record_recovery("heal_failed")


# ---------------------------------------------------------------------------
# offline stream verification: `python -m coda_tpu.cli replay-serve <dir>`
# ---------------------------------------------------------------------------

def verify_session_stream(store, meta: dict, rows, sid: str = "?") -> dict:
    """Replay one stream into a fresh slab slot and verify it bitwise.

    Returns ``{parity, rounds}``; raises :class:`ReplayMismatch` (or
    ValueError for a structurally unusable stream) otherwise."""
    v_err = _stream_version_error(meta)
    if v_err is not None:
        raise ValueError(v_err)
    task = meta.get("task")
    if task not in store.tasks():
        raise ValueError(f"stream's task {task!r} not loaded")
    want = meta.get("digest")
    have = store.task_meta(task).get("digest")
    if want and want != have:
        raise ValueError(
            f"dataset digest mismatch: stream recorded {want}, loaded "
            f"data hashes to {have}")
    kwargs = {k: v for k, v in (meta.get("spec_kwargs") or [])}
    spec = SelectorSpec.create(meta.get("method", "coda"),
                               acq_batch=int(meta.get("acq_batch", 1)),
                               **kwargs)
    # a prior-seeded stream verifies against the SAME applied-prior
    # record its meta stamped at admission (pool state since is moot)
    sess = store.open(task, spec, seed=int(meta.get("seed", 0)),
                      prior=meta.get("surrogate_prior"))
    try:
        rows = data_rows(rows)
        replay_rows_into_slot(sess.bucket, sess.slot, rows, sid=sid)
    finally:
        store.close(sess.sid)
    return {"parity": True, "rounds": len(rows)}


def replay_serve_main(argv=None) -> int:
    """``python -m coda_tpu.cli replay-serve <record-dir> [...]``: verify
    every serving-session JSONL stream in a record dir by bitwise replay
    against a fresh slab (exit 2 on any divergence) — the offline twin of
    ``cli replay`` for the interactive-session records."""
    import argparse

    p = argparse.ArgumentParser(
        prog="coda_tpu.cli replay-serve",
        description="replay-verify serving session streams "
                    "(session_<id>.jsonl) bitwise against a fresh slab")
    p.add_argument("record_dir", help="a serve --record-dir")
    p.add_argument("--task", default=None)
    p.add_argument("--data-dir", default="data")
    p.add_argument("--synthetic", default=None, metavar="H,N,C",
                   help="the seeded synthetic task the server ran "
                        "(must match the recorded dataset digest)")
    p.add_argument("--platform", default=None)
    p.add_argument("--session", default=None,
                   help="verify only this session id")
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    from coda_tpu.cli import load_dataset
    from coda_tpu.serve.state import SessionStore

    if args.task or args.synthetic:
        ds = load_dataset(args)
    else:
        from coda_tpu.data import make_synthetic_task

        ds = make_synthetic_task(seed=0, H=8, N=512, C=10)
    store = SessionStore(capacity=2)
    store.register_task(ds.name, ds.preds)

    n_ok = n_bad = 0
    for sid, path in iter_session_streams(args.record_dir):
        if args.session and sid != args.session:
            continue
        try:
            meta, rows, closed = load_session_stream(path)
            meta = dict(meta, task=ds.name)  # verify against loaded data
            info = verify_session_stream(store, meta, rows, sid=sid)
        except Exception as e:
            print(f"  session {sid}: DIVERGED/unusable — {e}")
            n_bad += 1
            continue
        print(f"  session {sid}: PARITY ({info['rounds']} rounds"
              + (", closed" if closed else ", live") + ")")
        n_ok += 1
    print(f"verdict: {'PARITY' if n_bad == 0 else 'DIVERGED'} "
          f"({n_ok} verified, {n_bad} failed)")
    return 0 if n_bad == 0 else 2
