"""Replicated serve fleet: lifecycle, rolling restarts, peer paging.

``serve/router.py`` owns addressing (rendezvous sharding, health-driven
routing, per-session migration mechanics); this module owns the
replicas themselves:

  * **Spawn** — :class:`Fleet` builds N replicas from an ``app_factory``
    (each a full :class:`~coda_tpu.serve.ServeApp`: own slab, batcher,
    tier manager, recorder), registers them with a
    :class:`~coda_tpu.serve.router.SessionRouter`, and wires the
    fleet-level hooks.
  * **Rolling restart** — :meth:`rolling_restart` cycles every replica
    in sequence: evict from routing → drain-and-migrate its sessions to
    their new owners (each digest-verified on the PR 7 export/import
    path) → stop the old process state → stand up a fresh replica from
    the factory → wait for its warm pool (the ``/healthz`` readiness
    gate) → rejoin → minimal rebalance pulls its key range back. Zero
    dropped sessions and zero double-applied labels through the whole
    cycle is the committed ``BENCH_FLEET_*`` claim.
  * **Demotion-aware peer paging** — each replica's
    :class:`~coda_tpu.serve.tiering.TierManager` gets a ``page_out``
    hook: a watermark- or age-pressured warm session is offered to the
    least-loaded OTHER routable replica (imported there digest-verified,
    router re-pointed) before it is spilled to local disk. Fleet RAM
    becomes one pool instead of N silos.

The container demo (``scripts/serve_loadgen.py --fleet N``) runs the
whole fleet in one process with :class:`~coda_tpu.serve.router.
InprocReplica` handles; a real deployment points the same router at
``HttpReplica`` URLs — the router and this lifecycle logic are
handle-type agnostic.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from coda_tpu.serve.router import DeadReplica, InprocReplica, SessionRouter


class _DeadApp:
    """What a captured handle sees after its replica is SIGKILLed: every
    attribute access — any verb, any bookkeeping read — raises
    ``ConnectionError``, the way a dead process's socket would."""

    def __init__(self, rid: str):
        object.__setattr__(self, "_rid", rid)

    def __getattr__(self, name):
        raise ConnectionError(
            f"replica {self._rid} is dead (killed)")


class Fleet:
    """N serve replicas + one session router, managed together.

    ``app_factory(replica_id)`` returns an UNSTARTED ServeApp for that
    replica (the same factory serves initial spawn and rolling-restart
    respawn, so a restarted replica is configured identically).

    ``journal_path`` arms the router's migration journal (crash-
    consistent moves — see ``serve/journal.py``); ``fault_spec`` arms
    per-edge transport chaos (``serve/faults.py`` net_* names) shared by
    every replica handle's transport."""

    def __init__(self, app_factory: Callable, n_replicas: int = 3,
                 replica_ids: Optional[list] = None, telemetry=None,
                 peer_paging: bool = True, auto_rebalance: bool = True,
                 journal_path: Optional[str] = None,
                 fault_spec: Optional[str] = None,
                 health_hysteresis: int = 2,
                 tracing: bool = True,
                 slo_fast_s: float = 300.0, slo_slow_s: float = 3600.0,
                 slo_store=None):
        from coda_tpu.serve.faults import FaultInjector

        self.app_factory = app_factory
        self.replica_ids = list(replica_ids or
                                [f"r{i}" for i in range(n_replicas)])
        self.apps: dict[str, object] = {}
        self.router = SessionRouter(
            telemetry=telemetry, auto_rebalance=auto_rebalance,
            journal_path=journal_path,
            faults=FaultInjector(fault_spec) if fault_spec else None,
            health_hysteresis=health_hysteresis,
            tracing=tracing, slo_fast_s=slo_fast_s, slo_slow_s=slo_slow_s,
            slo_store=slo_store)
        self.router.kill_hook = self.kill_replica
        self.peer_paging = peer_paging
        self.kills: dict[str, int] = {}
        for rid in self.replica_ids:
            self._spawn(rid)
        if journal_path is not None:
            # resolve any in-doubt moves a previous incarnation left
            # behind BEFORE this fleet serves a verb. Recovery PROBES
            # replica state, and a freshly spawned fleet has not crash-
            # restored its streams yet — resolving against empty stores
            # would terminally misjudge a move whose import actually
            # landed (and later crash restore would resurrect BOTH
            # copies). Restore first, then resolve.
            if self.router.journal is not None and \
                    self.router.journal.in_doubt():
                for rid, app in self.apps.items():
                    rdir = getattr(app.recorder, "out_dir", None)
                    if rdir:
                        try:
                            app.restore_sessions(rdir)
                        except Exception:
                            pass  # recovery still probes; worst case a
                            #       move resolves as restored-at-source
            self.journal_recovery = self.router.recover_from_journal()

    @property
    def peer_pages(self) -> int:
        """Fleet-wide successful peer pages (the router's counter is the
        single source of truth — one event, one counter)."""
        return self.router.counters["peer_pages"]

    # -- lifecycle ---------------------------------------------------------
    def _spawn(self, rid: str):
        app = self.app_factory(rid)
        self.apps[rid] = app
        if self.peer_paging and getattr(app, "tiers", None) is not None:
            app.tiers.page_out = self._make_pager(rid)
        self.router.add_replica(rid, InprocReplica(rid, app),
                                rebalance=False)
        return app

    def start(self, warm: bool = True, poll_s: float = 0.25) -> "Fleet":
        for app in self.apps.values():
            app.start(warm=warm)
        self.router.start(poll_s=poll_s)
        return self

    def drain(self, timeout: float = 30.0) -> None:
        self.router.drain()
        for app in self.apps.values():
            app.drain(timeout=timeout)

    # -- SIGKILL semantics (the in-process fleet's process fault) ----------
    def kill_replica(self, rid: str) -> None:
        """Abrupt replica death: no drain, no export, no goodbye — the
        batcher stops mid-queue, the handle becomes a dead socket, and
        the router discovers the death exactly as it would cross-host
        (connection errors, breaker, health poll). Any handle reference
        captured BEFORE the kill (a mid-migration router) dies too — the
        old handle's app is swapped for a connection-refusing tombstone,
        because a SIGKILLed process answers nobody, however old their
        socket. The replica's record streams and spill log stay on disk
        for :meth:`revive_replica`'s crash restore."""
        app = self.apps.get(rid)
        if app is None:
            return
        self.kills[rid] = self.kills.get(rid, 0) + 1
        with self.router._lock:
            old = self.router.replicas.get(rid)
            self.router.replicas[rid] = DeadReplica(rid)
        if isinstance(old, InprocReplica):
            old.app = _DeadApp(rid)
        # stop the compute threads without any drain/flush (SIGKILL
        # leaves no time for either); the recorder's per-row flush is
        # the only durability, which is exactly the contract
        try:
            app.batcher.stop(drain=False, timeout=0.5)
        except Exception:
            pass
        if getattr(app, "tiers", None) is not None:
            try:
                app.tiers.stop()
            except Exception:
                pass

    def revive_replica(self, rid: str, warm: bool = True,
                       restore_dir: Optional[str] = None) -> dict:
        """Stand a killed replica back up from the factory (+ optional
        crash restore from its record dir) and let health re-admit it."""
        new_app = self.app_factory(rid)
        if self.peer_paging and getattr(new_app, "tiers", None) is not None:
            new_app.tiers.page_out = self._make_pager(rid)
        new_app.start(warm=warm)
        report = {}
        rdir = restore_dir or getattr(new_app.recorder, "out_dir", None)
        if rdir:
            report = new_app.restore_sessions(rdir)
        self.apps[rid] = new_app
        with self.router._lock:
            self.router.replicas[rid] = InprocReplica(rid, new_app)
        self.router._wire_handle(self.router.replicas[rid])
        return report

    # -- peer paging -------------------------------------------------------
    def _make_pager(self, src_rid: str):
        def _page_out(sid: str, payload: dict) -> bool:
            dst_rid = self._least_loaded(exclude={src_rid})
            if dst_rid is None:
                return False  # no routable peer: fall back to disk
            handle = self.router.replicas.get(dst_rid)
            if handle is None:
                return False
            # the move rides the router's migration gate like any other
            # migration: the tier manager already popped the warm entry,
            # so until the peer's import lands the session exists only
            # in this thread's hands — a verb arriving now must wait the
            # gate out, not 404
            gate = threading.Event()
            with self.router._lock:
                if self.router._migrating.get(sid) is not None:
                    return False  # a real migration owns the sid: yield
                self.router._migrating[sid] = gate
                # a peer page is an ownership change like any migration:
                # bump the epoch so the (sealed, but crash-restorable)
                # local stream can never serve a commit again
                epoch_next = self.router._epochs.get(sid, 0) + 1
            journal = self.router.journal
            mid = None
            if journal is not None:
                mid = journal.begin(sid, src_rid, dst_rid, epoch_next)
            payload = dict(payload, epoch=epoch_next)
            try:
                if mid is not None:
                    from coda_tpu.serve.journal import payload_digest

                    journal.record(mid, "exported",
                                   digest=payload_digest(payload),
                                   n_labeled=payload.get("n_labeled"))
                try:
                    handle.import_payload(payload)
                except Exception as e:
                    if mid is not None:
                        journal.record(mid, "aborted", reason=repr(e))
                    return False
                if mid is not None:
                    journal.record(mid, "imported")
                with self.router._lock:
                    self.router._placed[sid] = dst_rid
                    self.router._epochs[sid] = epoch_next
                    self.router.counters["peer_pages"] += 1
                if mid is not None:
                    # the page's "fence" is the tier manager's own
                    # cleanup (it pops the warm entry + seals the
                    # stream on our True), so commit right away
                    journal.record(mid, "committed", epoch=epoch_next,
                                   fenced=True)
                return True
            finally:
                with self.router._lock:
                    self.router._migrating.pop(sid, None)
                gate.set()

        return _page_out

    def _least_loaded(self, exclude=()) -> Optional[str]:
        best, best_n = None, None
        for rid in self.router.routable():
            if rid in exclude:
                continue
            handle = self.router.replicas.get(rid)
            if handle is None:
                continue
            try:
                n = handle.open_count()
            except Exception:
                continue
            if best_n is None or n < best_n:
                best, best_n = rid, n
        return best

    # -- rolling restart ---------------------------------------------------
    def restart_replica(self, rid: str, warm: bool = True,
                        ready_timeout: float = 120.0) -> dict:
        """One replica's zero-drop restart cycle (see module docstring).
        Returns the migration accounting for the gate's evidence."""
        t0 = time.perf_counter()
        # cordoned eviction: the health poller must not re-admit a
        # replica we are deliberately draining (its /healthz answers ok
        # until the old app actually stops); rejoin() lifts the cordon
        self.router.evict(rid, cordon=True)
        # drain-and-migrate: ONLY this replica's sessions move (their
        # owner over the remaining set), each export/import
        # digest-verified; the other replicas' sessions never move
        out_report = self.router._migrate_all_off(rid)
        if out_report.get("failed"):
            # a failed migration left its session on THIS replica (the
            # hold was lifted, "didn't move") — draining now would
            # discard it. One more pass (transient
            # peer pressure usually clears), then ABORT the restart:
            # the replica rejoins with its sessions intact, and the
            # restart fails attributably instead of dropping anyone.
            retry = self.router._migrate_all_off(rid)
            out_report = {
                "migrated": out_report.get("migrated", 0)
                + retry.get("migrated", 0),
                "failed": retry.get("failed", 0),
                "errors": retry.get("errors"),
            }
            if out_report["failed"]:
                self.router.rejoin(rid)
                raise RuntimeError(
                    f"replica {rid} restart aborted: "
                    f"{out_report['failed']} session(s) could not be "
                    f"migrated off ({out_report.get('errors')}); the "
                    "replica rejoined with its sessions intact")
        old = self.apps[rid]
        # span hand-off: the rebuild below discards the old app's
        # in-memory trace retention, so the router adopts every retained
        # per-trace payload first — a trace that crossed this replica
        # stays complete through the rolling restart (a CRASH-killed
        # replica hands off nothing; that loss is honest)
        spans = getattr(getattr(old, "telemetry", None), "spans", None)
        if spans is not None:
            self.router.adopt_trace_payloads(
                [spans.trace_payload(tid, process=rid)
                 for tid in spans.trace_ids()])
        old.drain(timeout=30.0)
        new_app = self.app_factory(rid)
        if self.peer_paging and getattr(new_app, "tiers", None) is not None:
            new_app.tiers.page_out = self._make_pager(rid)
        new_app.start(warm=warm)
        if not new_app.ready.wait(ready_timeout):
            raise TimeoutError(f"replica {rid} warm pool not ready after "
                               f"{ready_timeout}s")
        self.apps[rid] = new_app
        with self.router._lock:
            self.router.replicas[rid] = InprocReplica(rid, new_app)
        self.router._wire_handle(self.router.replicas[rid])
        self.router.rejoin(rid)
        # minimal rebalance: exactly the sids whose HRW owner is the
        # rejoined replica come home
        back_report = self.router.rebalance()
        out = {"replica": rid,
               "migrated_out": out_report.get("migrated", 0),
               "migrated_back": back_report.get("moved", 0),
               "failed": out_report.get("failed", 0)
               + back_report.get("failed", 0),
               "seconds": round(time.perf_counter() - t0, 3)}
        errors = (out_report.get("errors") or []) + \
            (back_report.get("errors") or [])
        if errors:
            out["errors"] = errors
        return out

    def rolling_restart(self, warm: bool = True) -> dict:
        """Restart EVERY replica in sequence — the fleet's zero-downtime
        deploy. The router keeps serving throughout; each replica's
        sessions ride two digest-verified migrations (out, then home)."""
        rounds = []
        for rid in list(self.replica_ids):
            rounds.append(self.restart_replica(rid, warm=warm))
        c = self.router.counters
        return {
            "replicas_restarted": len(rounds),
            "rounds": rounds,
            "migrations": c["migrations"],
            "migration_failures": c["migration_failures"],
            "sessions_dropped": c["sessions_dropped"],
            "migrations_via": dict(self.router.migrations_via),
        }

    # -- reads -------------------------------------------------------------
    def stats(self) -> dict:
        return self.router.stats()


def build_fleet(args, n_replicas: int, record_dir: Optional[str] = None,
                fault_spec: Optional[str] = None) -> Fleet:
    """A fleet from serve CLI args (the loadgen/demo entry): each replica
    is ``build_app(args)`` with its own spill/record sub-directories so
    replicas never share mutable disk state. A record dir also arms the
    router's migration journal (``<record_dir>/router_migrations.log``);
    ``fault_spec`` arms per-edge transport chaos (``--fleet-chaos``)."""
    import copy
    import os

    from coda_tpu.serve.server import build_app

    base_record = record_dir or getattr(args, "record_dir", None)

    def factory(rid: str):
        a = copy.copy(args)
        if getattr(args, "tier_spill_dir", None):
            a.tier_spill_dir = os.path.join(args.tier_spill_dir, rid)
        if base_record:
            a.record_dir = os.path.join(base_record, rid)
        return build_app(a)

    journal_path = (os.path.join(base_record, "router_migrations.log")
                    if base_record else None)
    # SLO alert flushes happen on the router's poll thread; hand the
    # sweeper a factory (not a live store) because TrackingStore's sqlite
    # connection is bound to the thread that creates it
    slo_store = None
    tracking_db = getattr(args, "tracking_db", None)
    if tracking_db:
        from coda_tpu.tracking.store import TrackingStore
        slo_store = (lambda db=tracking_db: TrackingStore(db))
    return Fleet(factory, n_replicas=n_replicas,
                 journal_path=journal_path, fault_spec=fault_spec,
                 tracing=not getattr(args, "no_trace", False),
                 slo_fast_s=getattr(args, "slo_fast_s", 300.0),
                 slo_slow_s=getattr(args, "slo_slow_s", 3600.0),
                 slo_store=slo_store)
