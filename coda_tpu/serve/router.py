"""Fleet session router: rendezvous sharding, health-driven re-routing.

Every serve headline number before this module was a single-process
ceiling: one slab, one batcher thread, one front door. The router is the
fleet's front door — it speaks the exact same HTTP/JSON surface as one
replica (``make_server(router, port)`` reuses ``AsyncHTTPServer``
unchanged), and shards sessions across N serve replicas:

  * **Placement is rendezvous (HRW) hashing** on the session id:
    ``owner(sid) = argmax_r blake2b(sid, r)`` over the routable replica
    set. Deterministic across processes (keyed hash, never Python's
    salted ``hash``), and minimal under topology change — adding or
    removing one of N replicas re-owns only ~1/N of the id space, which
    is exactly the set of sessions a rebalance has to move.
  * **Health drives the routing set**: each replica's ``/healthz``
    (ok | degraded | unready, PR 6/7) is polled; an unready or
    unreachable replica is evicted from routing (its verbs re-route),
    a recovered one rejoins — each transition triggering a minimal
    rebalance.
  * **Rebalancing is drain-and-migrate on the PR 7 export/import path**:
    a session moves by being quiesced (the tiering demotion protocol —
    an in-flight label ticket pins the session and the demotion loses
    cleanly, so the payload always contains every committed label),
    exported, and imported on its new owner, where the snapshot fast
    path verifies the posterior digest bitwise against the stream's
    last recorded digest (or falls back to bitwise stream replay) —
    EVERY migration is digest-verified by construction. The router
    holds a per-sid migration gate while a session is in flight;
    requests for it wait out the move and then land on the new owner,
    and a label retried across the move is absorbed by the replica's
    idempotent request-id dedupe.
  * **Added latency is attributed span-by-span**: every routed verb
    records a ``route/<verb>`` span on the ``host:router`` lane nesting
    a ``dispatch/<replica>`` span for the replica call — router overhead
    is the outer minus the inner, mechanically, in the same trace.json
    vocabulary as the batcher's tick/step spans.

Observability does not regress to per-replica curl loops: the router's
``/stats`` merges every replica's snapshot (plus aggregate sums and the
router's own counters), and ``/metrics`` renders the serve gauge
families once each with a ``replica`` label per sample (lint-clean
under ``telemetry/prometheus.lint``).

``serve/fleet.py`` owns replica lifecycle (spawn, rolling restart, peer
paging); this module owns addressing, health, and migration mechanics.
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from typing import Optional, Sequence

from coda_tpu.serve.state import BucketQuarantined, SlabFull, UnknownSession

#: how long a verb waits out an in-flight migration of its session
MIGRATION_WAIT_S = 30.0


# ---------------------------------------------------------------------------
# rendezvous (highest-random-weight) hashing
# ---------------------------------------------------------------------------

def rendezvous_score(sid: str, replica_id: str) -> int:
    """The HRW weight of (session, replica): a keyed 64-bit digest.

    ``blake2b`` (not Python's ``hash``, which is salted per process):
    owners must agree across the router, every replica, and any offline
    tool that recomputes the shard map."""
    h = hashlib.blake2b(digest_size=8)
    h.update(sid.encode())
    h.update(b"\x00")
    h.update(replica_id.encode())
    return int.from_bytes(h.digest(), "big")


def rendezvous_rank(sid: str, replica_ids: Sequence[str]) -> list[str]:
    """Replicas by descending HRW score (ties broken by id — total order
    so every process ranks identically). ``[0]`` is the owner; the rest
    is the failover order."""
    return sorted(replica_ids,
                  key=lambda rid: (-rendezvous_score(sid, rid), rid))


def rendezvous_owner(sid: str, replica_ids: Sequence[str]) -> str:
    if not replica_ids:
        raise SlabFull("no routable replicas")
    best = None
    best_key = None
    for rid in replica_ids:
        key = (-rendezvous_score(sid, rid), rid)
        if best_key is None or key < best_key:
            best, best_key = rid, key
    return best


# ---------------------------------------------------------------------------
# replica handles: in-process and HTTP
# ---------------------------------------------------------------------------

class InprocReplica:
    """One fleet member served by a ServeApp in this process (the
    container demo; also what the tests drive)."""

    def __init__(self, replica_id: str, app):
        self.replica_id = replica_id
        self.app = app

    # -- verbs (the router forwards these; exceptions flow through) --------
    def open(self, task=None, seed=None, sid=None):
        return self.app.open_session(task=task, seed=seed, sid=sid)

    def label(self, sid, label, idx=None, request_id=None):
        return self.app.label(sid, label, idx=idx, request_id=request_id)

    def labels(self, sid, labels, idx=None, request_id=None):
        return self.app.labels(sid, labels, idx=idx, request_id=request_id)

    def best(self, sid):
        return self.app.best(sid)

    def trace(self, sid):
        return self.app.trace(sid)

    def close(self, sid):
        return self.app.close_session(sid)

    def export(self, sid, close=False):
        return self.app.export_session(sid, close=close)

    def import_payload(self, payload):
        return self.app.import_session(payload)

    def stats(self):
        return self.app.stats()

    def healthz(self):
        return self.app.healthz()

    # -- fleet bookkeeping -------------------------------------------------
    def has_session(self, sid) -> bool:
        return self.app.store.alive(sid) or (
            self.app.tiers is not None and self.app.tiers.parked(sid))

    def open_sids(self) -> list[str]:
        return self.app.list_sessions()["sessions"]

    def open_count(self) -> int:
        n = self.app.store.live_sessions()
        if self.app.tiers is not None:
            c = self.app.tiers.counts()
            n = c["hot"] + c["warm"] + c["cold"]
        return n

    def export_for_migration(self, sid) -> dict:
        """Quiesce-then-export: ride the tiering demotion protocol (it
        loses cleanly to any in-flight label ticket and wins once the
        ticket resolves) so the payload always carries every committed
        label; the export's ``close=True`` is the drain handoff — the
        source forgets the session the moment the payload exists."""
        app = self.app
        if app.tiers is not None:
            for _ in range(500):
                if not app.store.alive(sid):
                    break  # already parked (or closed) — export serves it
                if app.tiers.try_demote(sid):
                    break
                time.sleep(0.002)
        return app.export_session(sid, close=True)


class HttpReplica:
    """One fleet member behind a base URL (a real multi-process fleet).

    Maps the HTTP error envelope back onto the exceptions the in-process
    verbs raise, so the router's own front door re-encodes them
    identically no matter which handle type served the request."""

    def __init__(self, replica_id: str, url: str, timeout: float = 60.0):
        self.replica_id = replica_id
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _req(self, method, path, body=None):
        import json as _json
        import urllib.error
        import urllib.request

        data = None if body is None else _json.dumps(body).encode()
        req = urllib.request.Request(
            self.url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return _json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                msg = _json.loads(e.read()).get("error", "")
            except Exception:
                msg = str(e)
            if e.code == 404:
                raise UnknownSession(msg or path)
            if e.code == 503:
                raise BucketQuarantined(msg) if "healing" in msg \
                    else SlabFull(msg)
            if e.code == 409:
                from coda_tpu.serve.recovery import ImportRejected

                raise ImportRejected(msg)
            if e.code == 504:
                raise TimeoutError(msg)
            raise RuntimeError(f"{e.code}: {msg}")

    def open(self, task=None, seed=None, sid=None):
        body = {}
        if task is not None:
            body["task"] = task
        if seed is not None:
            body["seed"] = seed
        if sid is not None:
            body["session"] = sid
        return self._req("POST", "/session", body)

    def label(self, sid, label, idx=None, request_id=None):
        body = {"label": label}
        if idx is not None:
            body["idx"] = idx
        if request_id is not None:
            body["request_id"] = request_id
        return self._req("POST", f"/session/{sid}/label", body)

    def labels(self, sid, labels, idx=None, request_id=None):
        body = {"labels": list(labels)}
        if idx is not None:
            body["idx"] = idx
        if request_id is not None:
            body["request_id"] = request_id
        return self._req("POST", f"/session/{sid}/labels", body)

    def best(self, sid):
        return self._req("GET", f"/session/{sid}/best")

    def trace(self, sid):
        return self._req("GET", f"/session/{sid}/trace")

    def close(self, sid):
        return self._req("DELETE", f"/session/{sid}")

    def export(self, sid, close=False):
        return self._req("POST", f"/session/{sid}/export",
                         {"close": bool(close)})

    def import_payload(self, payload):
        return self._req("POST", "/session/import", payload)

    def stats(self):
        return self._req("GET", "/stats")

    def healthz(self):
        try:
            return self._req("GET", "/healthz")
        except SlabFull:
            # a 503 here is the replica saying "unready" — report it as
            # the healthz body would
            return {"ok": False, "ready": False, "status": "unready",
                    "draining": False, "problems": ["unready"]}

    def has_session(self, sid) -> bool:
        try:
            self.best(sid)
            return True
        except UnknownSession:
            return False
        except (SlabFull, BucketQuarantined):
            return True  # restoring/healing: it exists

    def open_sids(self) -> list[str]:
        return list((self._req("GET", "/sessions") or {})
                    .get("sessions", []))

    def open_count(self) -> int:
        st = self.stats()
        return int(st.get("open_sessions") or 0)

    def export_for_migration(self, sid) -> dict:
        return self.export(sid, close=True)


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class SessionRouter:
    """The fleet's front door (duck-types ServeApp's verb surface, so
    ``make_server(router, port)`` serves it over the same asyncio HTTP
    stack a single replica uses).

    Construction takes ``{replica_id: handle}``; :meth:`start` begins
    health polling. Topology changes (eviction, rejoin,
    :meth:`add_replica` / :meth:`remove_replica`) trigger
    :meth:`rebalance` — drain-and-migrate of exactly the minimal re-owned
    key range."""

    def __init__(self, replicas: Optional[dict] = None, telemetry=None,
                 auto_rebalance: bool = True):
        from concurrent.futures import ThreadPoolExecutor

        from coda_tpu.serve.metrics import ServeMetrics
        from coda_tpu.telemetry import Telemetry

        self._lock = threading.RLock()
        self.replicas: dict[str, object] = dict(replicas or {})
        self._routable: set[str] = set(self.replicas)
        self._health: dict[str, str] = {rid: "ok" for rid in self.replicas}
        # deliberate off-owner placements (peer paging, mid-rebalance):
        # sid -> replica id; consulted before the HRW owner
        self._placed: dict[str, str] = {}
        # operator-evicted replicas the health poller must NOT re-admit
        # (a draining replica's /healthz still answers ok until it
        # stops; rejoin() lifts the cordon explicitly)
        self._cordoned: set[str] = set()
        # per-sid migration gates: verbs wait these out, then re-locate
        self._migrating: dict[str, threading.Event] = {}
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.metrics = ServeMetrics()   # router-level request accounting
        self.draining = False
        self.auto_rebalance = auto_rebalance
        self.counters = {
            "requests_routed": 0, "reroutes": 0, "migrations": 0,
            "migration_failures": 0, "evictions": 0, "rejoins": 0,
            "rebalances": 0, "peer_pages": 0, "sessions_dropped": 0,
        }
        self.migrations_via: dict[str, int] = {}   # snapshot vs replay
        self.routed_to: dict[str, int] = {rid: 0 for rid in self.replicas}
        self._executor = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="router-verb")
        self._running = False
        self._poll_thread: Optional[threading.Thread] = None
        self._wakeup = threading.Event()
        self.ready = threading.Event()
        if self.replicas:
            self.ready.set()
        # the span vocabulary the trace-based attribution keys on
        self._spans = self.telemetry.spans

    # -- topology ----------------------------------------------------------
    def add_replica(self, replica_id: str, handle, rebalance: bool = True
                    ) -> None:
        with self._lock:
            self.replicas[replica_id] = handle
            self._routable.add(replica_id)
            self._health[replica_id] = "ok"
            self.routed_to.setdefault(replica_id, 0)
            self.ready.set()
        if rebalance:
            self.rebalance()

    def remove_replica(self, replica_id: str, migrate: bool = True) -> dict:
        """Drain one replica out of the fleet: evict it from routing,
        migrate every session it holds to the sessions' new HRW owners
        (each digest-verified), then forget the handle. Returns the
        migration report."""
        with self._lock:
            if replica_id not in self.replicas:
                return {"migrated": 0}
            self._routable.discard(replica_id)
        report = (self._migrate_all_off(replica_id) if migrate
                  else {"migrated": 0})
        with self._lock:
            self.replicas.pop(replica_id, None)
            self._health.pop(replica_id, None)
        return report

    def evict(self, replica_id: str, cordon: bool = False) -> None:
        """Take a replica out of routing without forgetting it (health
        eviction: it may recover and rejoin). ``cordon`` additionally
        bars the health poller from re-admitting it — the drain flow,
        where the replica's /healthz keeps answering ok until it
        actually stops."""
        with self._lock:
            if cordon:
                self._cordoned.add(replica_id)
            if replica_id in self._routable:
                self._routable.discard(replica_id)
                self.counters["evictions"] += 1

    def rejoin(self, replica_id: str) -> None:
        with self._lock:
            self._cordoned.discard(replica_id)
            if replica_id in self.replicas and \
                    replica_id not in self._routable:
                self._routable.add(replica_id)
                self.counters["rejoins"] += 1

    def routable(self) -> list[str]:
        with self._lock:
            return sorted(self._routable)

    def owner_of(self, sid: str) -> str:
        return rendezvous_owner(sid, self.routable())

    # -- health ------------------------------------------------------------
    def check_health(self) -> dict:
        """One poll of every replica's /healthz: unreachable or unready
        replicas leave the routing set, recovered ones rejoin. Returns
        {replica: status}; topology changes trigger a rebalance when
        ``auto_rebalance``."""
        statuses: dict[str, str] = {}
        with self._lock:
            items = list(self.replicas.items())
        changed = False
        for rid, handle in items:
            try:
                hz = handle.healthz()
                status = hz.get("status") or (
                    "ok" if hz.get("ready") else "unready")
                if hz.get("draining"):
                    status = "draining"
            except Exception:
                status = "unreachable"
            statuses[rid] = status
            routable = status in ("ok", "degraded")
            with self._lock:
                was = rid in self._routable
                cordoned = rid in self._cordoned
                self._health[rid] = status
            if routable and not was and not cordoned:
                self.rejoin(rid)
                changed = True
            elif not routable and was:
                self.evict(rid)
                changed = True
        if changed and self.auto_rebalance:
            try:
                self.rebalance()
            except Exception:
                pass  # the poller must survive a mid-rebalance hiccup
        return statuses

    def start(self, poll_s: float = 0.25) -> "SessionRouter":
        if self._poll_thread is not None:
            return self
        self._running = True

        def _loop():
            while self._running:
                try:
                    self.check_health()
                except Exception:
                    pass
                self._wakeup.wait(poll_s)
                self._wakeup.clear()

        self._poll_thread = threading.Thread(
            target=_loop, daemon=True, name="router-health")
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        self._wakeup.set()
        t, self._poll_thread = self._poll_thread, None
        if t is not None:
            t.join(timeout=5.0)

    def drain(self, timeout: float = 30.0) -> None:
        self.draining = True
        self.stop()
        self._executor.shutdown(wait=False)

    # -- location ----------------------------------------------------------
    def _locate(self, sid: str) -> str:
        gate = None
        with self._lock:
            gate = self._migrating.get(sid)
        if gate is not None:
            gate.wait(MIGRATION_WAIT_S)
        with self._lock:
            rid = self._placed.get(sid)
            if rid is not None and rid in self.replicas:
                # an off-owner placement on an evicted-but-known replica
                # still resolves: it serves its sessions while draining
                return rid
            routable = sorted(self._routable)
        return rendezvous_owner(sid, routable)

    def _find(self, sid: str, exclude=()) -> Optional[str]:
        """Search the fleet for a session that is not where the shard map
        says (a topology change the rebalance has not caught up with).
        ALL known replicas are probed — an evicted-but-draining replica
        still serves its existing sessions until they migrate off it —
        in rendezvous-rank order, the most likely ex-owners first."""
        with self._lock:
            candidates = [r for r in self.replicas if r not in exclude]
        for rid in rendezvous_rank(sid, candidates):
            try:
                if self.replicas[rid].has_session(sid):
                    return rid
            except Exception:
                continue
        return None

    def _forward(self, verb: str, sid: str, fn):
        """Route one verb: locate -> dispatch (with the route span
        nesting the replica dispatch span) -> on UnknownSession, search
        the fleet and re-route once; on a dead replica, evict and
        fail over."""
        with self._spans.span(f"route/{verb}", lane="host:router"):
            last_err: Optional[BaseException] = None
            for attempt in range(3):
                rid = self._locate(sid)
                with self._lock:
                    handle = self.replicas.get(rid)
                if handle is None:
                    continue
                try:
                    with self._spans.span(f"dispatch/{rid}",
                                          lane="host:router"):
                        out = fn(handle)
                    with self._lock:
                        self.counters["requests_routed"] += 1
                        self.routed_to[rid] = \
                            self.routed_to.get(rid, 0) + 1
                    return out
                except UnknownSession as e:
                    last_err = e
                    with self._lock:
                        gate = self._migrating.get(sid)
                    if gate is not None:
                        # we located the source BEFORE its migration gate
                        # went up and dispatched after the export-close:
                        # mid-move the payload exists only in the
                        # migrating thread's hands, so neither side
                        # answers. Wait the move out, then re-locate —
                        # never a 404 for a session that is merely in
                        # transit.
                        gate.wait(MIGRATION_WAIT_S)
                        continue
                    found = self._find(sid, exclude={rid})
                    if found is None:
                        if attempt < 2:
                            # a migration's gate may have been popped
                            # between our dispatch and the check above —
                            # one short beat, then re-locate
                            time.sleep(0.01)
                            continue
                        raise
                    with self._lock:
                        self._placed[sid] = found
                        self.counters["reroutes"] += 1
                except (ConnectionError, OSError) as e:
                    # replica went away under us: evict, let health/
                    # rebalance recover it, and fail over this request
                    last_err = e
                    self.evict(rid)
            raise (last_err or SlabFull("no routable replica answered"))

    # -- the front-door verb surface (ServeApp-compatible) -----------------
    def open_session(self, task: Optional[str] = None,
                     seed: Optional[int] = None) -> dict:
        if self.draining:
            from coda_tpu.serve.server import Draining

            raise Draining()
        # the router mints the sid so placement is HRW on the id BEFORE
        # the replica admits it (the replica honors the pinned id)
        sid = uuid.uuid4().hex
        with self._spans.span("route/open", lane="host:router"):
            last_err: Optional[BaseException] = None
            for _ in range(3):
                owner = rendezvous_owner(sid, self.routable())
                with self._lock:
                    handle = self.replicas.get(owner)
                if handle is None:
                    continue  # removed between routable() and lookup
                try:
                    with self._spans.span(f"dispatch/{owner}",
                                          lane="host:router"):
                        out = handle.open(task=task, seed=seed, sid=sid)
                except (ConnectionError, OSError) as e:
                    # dead owner inside the health-poll window: evict it
                    # (like every _forward verb does) and re-own the sid
                    # over the survivors instead of bouncing the client
                    last_err = e
                    self.evict(owner)
                    continue
                with self._lock:
                    self.counters["requests_routed"] += 1
                    self.routed_to[owner] = \
                        self.routed_to.get(owner, 0) + 1
                return out
            raise (last_err or SlabFull("no routable replica answered"))

    async def open_session_async(self, task=None, seed=None) -> dict:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, lambda: self.open_session(task, seed))

    def label(self, sid: str, label, idx=None, request_id=None) -> dict:
        return self._forward(
            "label", sid,
            lambda h: h.label(sid, label, idx=idx, request_id=request_id))

    async def label_async(self, sid, label, idx=None,
                          request_id=None) -> dict:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: self.label(sid, label, idx=idx, request_id=request_id))

    def labels(self, sid: str, labels, idx=None, request_id=None) -> dict:
        return self._forward(
            "labels", sid,
            lambda h: h.labels(sid, labels, idx=idx,
                               request_id=request_id))

    async def labels_async(self, sid, labels, idx=None,
                           request_id=None) -> dict:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: self.labels(sid, labels, idx=idx,
                                request_id=request_id))

    def best(self, sid: str) -> dict:
        return self._forward("best", sid, lambda h: h.best(sid))

    def trace(self, sid: str) -> dict:
        return self._forward("trace", sid, lambda h: h.trace(sid))

    def close_session(self, sid: str) -> dict:
        out = self._forward("close", sid, lambda h: h.close(sid))
        with self._lock:
            self._placed.pop(sid, None)
        return out

    def export_session(self, sid: str, close: bool = False) -> dict:
        out = self._forward("export", sid,
                            lambda h: h.export(sid, close=close))
        if close:
            with self._lock:
                self._placed.pop(sid, None)
        return out

    def import_session(self, payload: dict) -> dict:
        if self.draining:
            from coda_tpu.serve.server import Draining

            raise Draining()
        sid = str(payload.get("session") or "")
        owner = rendezvous_owner(sid, self.routable())
        with self._spans.span("route/import", lane="host:router"):
            with self._lock:
                handle = self.replicas[owner]
            with self._spans.span(f"dispatch/{owner}", lane="host:router"):
                return handle.import_payload(payload)

    # -- migration ---------------------------------------------------------
    def migrate_session(self, sid: str, src_rid: str, dst_rid: str) -> dict:
        """Move one session: gate its verbs, quiesce-export from the
        source (drain handoff — the source forgets it), import on the
        destination (digest-verified snapshot or bitwise stream replay),
        un-gate. On an import failure the payload is restored to the
        SOURCE so the session is never dropped."""
        gate = threading.Event()
        with self._lock:
            if self._migrating.get(sid) is not None:
                return {"skipped": "already migrating"}
            self._migrating[sid] = gate
            src = self.replicas.get(src_rid)
            dst = self.replicas.get(dst_rid)
        info: dict = {}
        try:
            if src is None or dst is None:
                return {"skipped": "replica gone"}
            try:
                payload = src.export_for_migration(sid)
            except UnknownSession:
                return {"skipped": "closed"}
            try:
                res = None
                for i in range(8):
                    try:
                        res = dst.import_payload(payload)
                        break
                    except SlabFull:
                        # transient admission pressure on the peer
                        # (every slot momentarily pinned): a migration
                        # must out-wait it, not fail the move
                        if i == 7:
                            raise
                        time.sleep(0.01 * (i + 1))
                via = res.get("restored_via", "?")
                with self._lock:
                    # home placement needs no override; an off-owner
                    # destination (peer paging) keeps one
                    owner = rendezvous_owner(sid, sorted(self._routable))
                    if dst_rid == owner:
                        self._placed.pop(sid, None)
                    else:
                        self._placed[sid] = dst_rid
                    self.counters["migrations"] += 1
                    self.migrations_via[via] = \
                        self.migrations_via.get(via, 0) + 1
                info = {"migrated": sid, "from": src_rid, "to": dst_rid,
                        "via": via}
            except BaseException as e:
                # put it back where it came from — a failed migration
                # must degrade to "didn't move", never to "gone"
                with self._lock:
                    self.counters["migration_failures"] += 1
                try:
                    src.import_payload(payload)
                    with self._lock:
                        self._placed[sid] = src_rid
                except BaseException:
                    with self._lock:
                        self.counters["sessions_dropped"] += 1
                    raise
                info = {"failed": sid, "error": repr(e)}
            return info
        finally:
            with self._lock:
                self._migrating.pop(sid, None)
            gate.set()

    def _migrate_all_off(self, src_rid: str) -> dict:
        """Drain-and-migrate every session off one replica to the
        sessions' HRW owners over the remaining routable set."""
        with self._lock:
            handle = self.replicas.get(src_rid)
            routable = sorted(self._routable - {src_rid})
        if handle is None or not routable:
            return {"migrated": 0}
        moved = failed = 0
        fail_errors: list = []
        for sid in handle.open_sids():
            dst = rendezvous_owner(sid, routable)
            info = self.migrate_session(sid, src_rid, dst)
            if "migrated" in info:
                moved += 1
            elif "failed" in info:
                failed += 1
                fail_errors.append(info.get("error"))
        out = {"migrated": moved, "failed": failed}
        if fail_errors:
            out["errors"] = fail_errors[:10]
        return out

    def rebalance(self, full: bool = False) -> dict:
        """Move every session to its HRW owner over the CURRENT routable
        set — after a topology change this is exactly the minimal
        re-owned key range (sessions whose owner is unchanged never
        move). ``full=True`` also re-homes deliberate off-owner
        placements (peer-paged sessions); the default leaves them where
        the pressure balancing put them."""
        moved = failed = 0
        fail_errors: list = []
        with self._lock:
            items = [(rid, self.replicas[rid])
                     for rid in sorted(self._routable)]
            routable = sorted(self._routable)
            placed = dict(self._placed)
        for rid, handle in items:
            try:
                sids = handle.open_sids()
            except Exception:
                continue
            for sid in sids:
                if not full and placed.get(sid) == rid:
                    continue  # deliberately placed here (peer paging)
                owner = rendezvous_owner(sid, routable)
                if owner == rid:
                    continue
                info = self.migrate_session(sid, rid, owner)
                if "migrated" in info:
                    moved += 1
                elif "failed" in info:
                    failed += 1
                    fail_errors.append(info.get("error"))
        with self._lock:
            self.counters["rebalances"] += 1
        out = {"moved": moved, "failed": failed}
        if fail_errors:
            out["errors"] = fail_errors[:10]
        return out

    def list_sessions(self) -> dict:
        """Union of every replica's addressable sessions (GET /sessions
        on the router — the fleet-wide worklist)."""
        out: list[str] = []
        seen: set = set()
        with self._lock:
            items = list(self.replicas.items())
        for rid, handle in items:
            try:
                fresh = [s for s in handle.open_sids() if s not in seen]
            except Exception:
                continue
            out += fresh
            seen.update(fresh)
        return {"sessions": out}

    # -- observability -----------------------------------------------------
    def healthz(self) -> dict:
        with self._lock:
            health = dict(self._health)
            routable = sorted(self._routable)
            n_replicas = len(self.replicas)
        ready = bool(routable) and not self.draining
        problems = [f"replica_{rid}_{st}" for rid, st in sorted(
            health.items()) if st not in ("ok",)]
        status = ("unready" if not routable
                  else "degraded" if len(routable) < n_replicas or problems
                  else "ok")
        return {"ok": ready, "ready": bool(routable),
                "draining": self.draining, "status": status,
                "role": "router", "replicas": health,
                "routable": routable, "problems": problems}

    def stats(self) -> dict:
        """The merged fleet snapshot: per-replica /stats sections, the
        aggregate sums a dashboard wants, and the router's own routing/
        migration counters — one endpoint, not a per-replica curl loop."""
        with self._lock:
            items = list(self.replicas.items())
            counters = dict(self.counters)
            via = dict(self.migrations_via)
            routed = dict(self.routed_to)
            routable = sorted(self._routable)
            health = dict(self._health)
            placed = len(self._placed)
        per_replica: dict[str, dict] = {}
        for rid, handle in items:
            try:
                per_replica[rid] = handle.stats()
            except Exception as e:
                per_replica[rid] = {"error": repr(e)}
        agg_keys = ("open_sessions", "slab_occupancy", "dispatches",
                    "requests", "sessions_opened", "sessions_closed",
                    "demotions", "wakes", "hibernates", "peer_pages")
        aggregate = {k: sum(int(s.get(k) or 0) for s in per_replica.values()
                            if "error" not in s) for k in agg_keys}
        return {
            "role": "router",
            "replicas": per_replica,
            "aggregate": aggregate,
            "router": {
                "routable": routable,
                "health": health,
                "counters": counters,
                "migrations_via": via,
                "requests_to": routed,
                "placed_overrides": placed,
                "migration_verified": sum(via.values()),
            },
        }

    def render_metrics(self) -> str:
        """The merged /metrics exposition: router registry families plus
        every serve family rendered ONCE with per-replica labels."""
        from coda_tpu.telemetry.prometheus import render_fleet

        st = self.stats()
        return render_fleet(st["replicas"],
                            registry=self.telemetry.registry,
                            router_stats=st["router"])
