"""Fleet session router: rendezvous sharding, health-driven re-routing.

Every serve headline number before this module was a single-process
ceiling: one slab, one batcher thread, one front door. The router is the
fleet's front door — it speaks the exact same HTTP/JSON surface as one
replica (``make_server(router, port)`` reuses ``AsyncHTTPServer``
unchanged), and shards sessions across N serve replicas:

  * **Placement is rendezvous (HRW) hashing** on the session id:
    ``owner(sid) = argmax_r blake2b(sid, r)`` over the routable replica
    set. Deterministic across processes (keyed hash, never Python's
    salted ``hash``), and minimal under topology change — adding or
    removing one of N replicas re-owns only ~1/N of the id space, which
    is exactly the set of sessions a rebalance has to move.
  * **Health drives the routing set**: each replica's ``/healthz``
    (ok | degraded | unready, PR 6/7) is polled; an unready or
    unreachable replica is evicted from routing (its verbs re-route),
    a recovered one rejoins — each transition triggering a minimal
    rebalance.
  * **Rebalancing is drain-and-migrate on the PR 7 export/import path**:
    a session moves by being quiesced (the tiering demotion protocol —
    an in-flight label ticket pins the session and the demotion loses
    cleanly, so the payload always contains every committed label),
    exported, and imported on its new owner, where the snapshot fast
    path verifies the posterior digest bitwise against the stream's
    last recorded digest (or falls back to bitwise stream replay) —
    EVERY migration is digest-verified by construction. The router
    holds a per-sid migration gate while a session is in flight;
    requests for it wait out the move and then land on the new owner,
    and a label retried across the move is absorbed by the replica's
    idempotent request-id dedupe.
  * **Added latency is attributed span-by-span**: every routed verb
    records a ``route/<verb>`` span on the ``host:router`` lane nesting
    a ``dispatch/<replica>`` span for the replica call — router overhead
    is the outer minus the inner, mechanically, in the same trace.json
    vocabulary as the batcher's tick/step spans.

Observability does not regress to per-replica curl loops: the router's
``/stats`` merges every replica's snapshot (plus aggregate sums and the
router's own counters), and ``/metrics`` renders the serve gauge
families once each with a ``replica`` label per sample (lint-clean
under ``telemetry/prometheus.lint``).

``serve/fleet.py`` owns replica lifecycle (spawn, rolling restart, peer
paging); this module owns addressing, health, and migration mechanics.
"""

from __future__ import annotations

import collections
import hashlib
import re
import threading
import time
import uuid
from typing import Optional, Sequence

from coda_tpu.serve.state import (
    BucketQuarantined,
    SlabFull,
    StaleOwner,
    UnknownSession,
)
from coda_tpu.serve.transport import ReplicaTransport, ReplicaUnavailable

#: how long a verb waits out an in-flight migration of its session
MIGRATION_WAIT_S = 30.0


# ---------------------------------------------------------------------------
# rendezvous (highest-random-weight) hashing
# ---------------------------------------------------------------------------

def rendezvous_score(sid: str, replica_id: str) -> int:
    """The HRW weight of (session, replica): a keyed 64-bit digest.

    ``blake2b`` (not Python's ``hash``, which is salted per process):
    owners must agree across the router, every replica, and any offline
    tool that recomputes the shard map."""
    h = hashlib.blake2b(digest_size=8)
    h.update(sid.encode())
    h.update(b"\x00")
    h.update(replica_id.encode())
    return int.from_bytes(h.digest(), "big")


def rendezvous_rank(sid: str, replica_ids: Sequence[str]) -> list[str]:
    """Replicas by descending HRW score (ties broken by id — total order
    so every process ranks identically). ``[0]`` is the owner; the rest
    is the failover order."""
    return sorted(replica_ids,
                  key=lambda rid: (-rendezvous_score(sid, rid), rid))


def rendezvous_owner(sid: str, replica_ids: Sequence[str]) -> str:
    if not replica_ids:
        raise SlabFull("no routable replicas")
    best = None
    best_key = None
    for rid in replica_ids:
        key = (-rendezvous_score(sid, rid), rid)
        if best_key is None or key < best_key:
            best, best_key = rid, key
    return best


# ---------------------------------------------------------------------------
# replica handles: in-process and HTTP
# ---------------------------------------------------------------------------

class InprocReplica:
    """One fleet member served by a ServeApp in this process (the
    container demo; also what the tests drive).

    Every verb rides the same :class:`~coda_tpu.serve.transport.
    ReplicaTransport` policy layer the HTTP handle uses — transport
    can't actually fail in-process, but the parity buys two things: the
    per-edge chaos faults (``net_drop``/``net_delay``/``net_dup``/
    ``partition``/``flap_healthz``) inject here exactly as they would on
    a real socket, and the breaker/retry accounting the router reports
    is one code path, not two."""

    def __init__(self, replica_id: str, app, transport=None):
        self.replica_id = replica_id
        self.app = app
        self.transport = transport or ReplicaTransport(replica_id)

    # -- verbs (the router forwards these; exceptions flow through) --------
    def open(self, task=None, seed=None, sid=None, trace=None):
        return self.transport.call(
            "open", lambda t: self.app.open_session(task=task, seed=seed,
                                                    sid=sid,
                                                    trace_ctx=trace))

    def label(self, sid, label, idx=None, request_id=None, epoch=None,
              trace=None):
        return self.transport.call(
            "label",
            lambda t: self.app.label(sid, label, idx=idx,
                                     request_id=request_id, epoch=epoch,
                                     trace_ctx=trace),
            idempotent=request_id is not None)

    def labels(self, sid, labels, idx=None, request_id=None, epoch=None,
               trace=None):
        return self.transport.call(
            "labels",
            lambda t: self.app.labels(sid, labels, idx=idx,
                                      request_id=request_id, epoch=epoch,
                                      trace_ctx=trace),
            idempotent=request_id is not None)

    def best(self, sid, epoch=None, trace=None):
        return self.transport.call(
            "best", lambda t: self.app.best(sid, epoch=epoch,
                                            trace_ctx=trace))

    def trace(self, sid, epoch=None):
        return self.transport.call(
            "trace", lambda t: self.app.trace(sid, epoch=epoch))

    def trace_by_id(self, trace_id):
        # this replica's retained spans for one distributed trace (the
        # router's stitcher fans this out across the fleet)
        return self.transport.call(
            "trace_by_id", lambda t: self.app.trace_by_id(trace_id),
            idempotent=True)

    def close(self, sid, epoch=None):
        return self.transport.call(
            "close", lambda t: self.app.close_session(sid, epoch=epoch))

    def export(self, sid, close=False, hold=False):
        return self.transport.call(
            "export", lambda t: self.app.export_session(sid, close=close,
                                                        hold=hold))

    def fence(self, sid, drop=True):
        return self.transport.call(
            "fence", lambda t: self.app.end_migration(sid, drop=drop),
            idempotent=True)

    def import_payload(self, payload):
        return self.transport.call(
            "import", lambda t: self.app.import_session(payload))

    def stats(self):
        return self.transport.call("stats", lambda t: self.app.stats())

    def sync_prior(self, pool_snap=None):
        return self.transport.call(
            "prior_sync", lambda t: self.app.sync_prior(pool_snap))

    def healthz(self):
        return self.transport.call("healthz",
                                   lambda t: self.app.healthz())

    # -- fleet bookkeeping -------------------------------------------------
    def has_session(self, sid) -> bool:
        return self.app.store.alive(sid) or (
            self.app.tiers is not None and self.app.tiers.parked(sid))

    def session_epoch(self, sid) -> Optional[int]:
        """The ownership epoch of this replica's copy, or None when it
        holds none (the journal-recovery probe)."""
        try:
            return int(self.app.session_epoch(sid)["epoch"])
        except UnknownSession:
            return None

    def open_sids(self) -> list[str]:
        return self.app.list_sessions()["sessions"]

    def open_count(self) -> int:
        n = self.app.store.live_sessions()
        if self.app.tiers is not None:
            c = self.app.tiers.counts()
            n = c["hot"] + c["warm"] + c["cold"]
        return n

    def export_for_migration(self, sid) -> dict:
        """The migration PREPARE: quiesce + hold + export WITHOUT close
        (``ServeApp.begin_migration``). The source keeps a recoverable —
        but held, uncommittable — copy until :meth:`fence` commits or
        aborts the move, so a crash or lost response anywhere in the
        window degrades to "didn't move", never "gone"."""
        return self.transport.call(
            "export", lambda t: self.app.begin_migration(sid),
            idempotent=True)


class DeadReplica:
    """The handle of a SIGKILLed in-process replica: every verb raises
    ``ConnectionError``, exactly what a real dead host's socket would do
    (``Fleet.kill_replica`` swaps this in; the health poller and the
    breaker then discover the death the same way they would cross-host)."""

    def __init__(self, replica_id: str):
        self.replica_id = replica_id
        self.transport = ReplicaTransport(replica_id)

    def _dead(self, *a, **k):
        raise ConnectionError(
            f"replica {self.replica_id} is dead (killed)")

    open = label = labels = best = trace = close = _dead
    export = fence = import_payload = stats = healthz = _dead
    export_for_migration = sync_prior = trace_by_id = _dead

    def has_session(self, sid) -> bool:
        raise ConnectionError(
            f"replica {self.replica_id} is dead (killed)")

    def session_epoch(self, sid):
        raise ConnectionError(
            f"replica {self.replica_id} is dead (killed)")

    def open_sids(self) -> list[str]:
        raise ConnectionError(
            f"replica {self.replica_id} is dead (killed)")

    def open_count(self) -> int:
        raise ConnectionError(
            f"replica {self.replica_id} is dead (killed)")


#: parses the epoch pair out of a StaleOwner error's HTTP message
_STALE_RE = re.compile(r"session ([0-9a-f]+):.*epoch (\d+).*epoch (\d+)")


class HttpReplica:
    """One fleet member behind a base URL (a real multi-process fleet).

    Maps the HTTP error envelope back onto the exceptions the in-process
    verbs raise, so the router's own front door re-encodes them
    identically no matter which handle type served the request. Every
    request rides the hardened transport (``serve/transport.py``): the
    per-verb deadline replaces the old fixed 60 s blanket, transport
    failures retry only when the verb is provably idempotent at the
    replica, a per-replica budget bounds the retry amplification, and a
    circuit breaker fails fast on a black-holed host."""

    def __init__(self, replica_id: str, url: str,
                 timeout: Optional[float] = None, transport=None,
                 deadlines: Optional[dict] = None, **transport_kw):
        self.replica_id = replica_id
        self.url = url.rstrip("/")
        dl = dict(deadlines or {})
        if timeout is not None:
            # legacy blanket timeout: now just a floor-raise on every
            # verb's deadline rather than the one number for everything
            from coda_tpu.serve.transport import VERB_DEADLINES

            for verb, d in VERB_DEADLINES.items():
                dl.setdefault(verb, max(d, float(timeout)))
        self.transport = transport or ReplicaTransport(
            replica_id, deadlines=dl, **transport_kw)

    def _req(self, method, path, body=None, timeout=60.0, trace=None):
        import json as _json
        import socket
        import urllib.error
        import urllib.request

        data = None if body is None else _json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            # the wire form of the trace context: the replica's serve
            # span parents to OUR span, so cross-process stitching gets
            # one causal chain (same header both handle types speak)
            from coda_tpu.telemetry.trace import TRACE_HEADER

            headers[TRACE_HEADER] = trace.header()
        req = urllib.request.Request(
            self.url + path, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return _json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                msg = _json.loads(e.read()).get("error", "")
            except Exception:
                msg = str(e)
            if e.code == 404:
                raise UnknownSession(msg or path)
            if e.code == 503:
                raise BucketQuarantined(msg) if "healing" in msg \
                    else SlabFull(msg)
            if e.code == 409:
                if msg.startswith("stale owner"):
                    m = _STALE_RE.search(msg)
                    raise StaleOwner(m.group(1) if m else path,
                                     have=int(m.group(2)) if m else 0,
                                     want=int(m.group(3)) if m else 0)
                from coda_tpu.serve.recovery import ImportRejected

                raise ImportRejected(msg)
            if e.code == 504:
                raise TimeoutError(msg)
            raise RuntimeError(f"{e.code}: {msg}")
        except urllib.error.URLError as e:
            # normalize the urllib wrapper onto the transport-error types
            # the retry/breaker policy classifies
            reason = getattr(e, "reason", e)
            if isinstance(reason, (ConnectionError, socket.timeout,
                                   TimeoutError, OSError)):
                raise reason
            raise ConnectionError(str(e))

    def _call(self, verb, method, path, body=None, idempotent=False,
              trace=None):
        return self.transport.call(
            verb, lambda t: self._req(method, path, body, timeout=t,
                                      trace=trace),
            idempotent=idempotent)

    @staticmethod
    def _stamp(body: dict, epoch) -> dict:
        if epoch is not None:
            body["epoch"] = int(epoch)
        return body

    def open(self, task=None, seed=None, sid=None, trace=None):
        body = {}
        if task is not None:
            body["task"] = task
        if seed is not None:
            body["seed"] = seed
        if sid is not None:
            body["session"] = sid
        return self._call("open", "POST", "/session", body, trace=trace)

    def label(self, sid, label, idx=None, request_id=None, epoch=None,
              trace=None):
        body = self._stamp({"label": label}, epoch)
        if idx is not None:
            body["idx"] = idx
        if request_id is not None:
            body["request_id"] = request_id
        return self._call("label", "POST", f"/session/{sid}/label", body,
                          idempotent=request_id is not None, trace=trace)

    def labels(self, sid, labels, idx=None, request_id=None, epoch=None,
               trace=None):
        body = self._stamp({"labels": list(labels)}, epoch)
        if idx is not None:
            body["idx"] = idx
        if request_id is not None:
            body["request_id"] = request_id
        return self._call("labels", "POST", f"/session/{sid}/labels", body,
                          idempotent=request_id is not None, trace=trace)

    def best(self, sid, epoch=None, trace=None):
        q = f"?epoch={int(epoch)}" if epoch is not None else ""
        return self._call("best", "GET", f"/session/{sid}/best{q}",
                          trace=trace)

    def trace(self, sid, epoch=None):
        q = f"?epoch={int(epoch)}" if epoch is not None else ""
        return self._call("trace", "GET", f"/session/{sid}/trace{q}")

    def trace_by_id(self, trace_id):
        return self._call("trace_by_id", "GET", f"/trace/id/{trace_id}",
                          idempotent=True)

    def close(self, sid, epoch=None):
        return self._call("close", "DELETE", f"/session/{sid}",
                          self._stamp({}, epoch) or None)

    def export(self, sid, close=False, hold=False):
        return self._call("export", "POST", f"/session/{sid}/export",
                          {"close": bool(close), "hold": bool(hold)})

    def fence(self, sid, drop=True):
        return self._call("fence", "POST", f"/session/{sid}/fence",
                          {"drop": bool(drop)}, idempotent=True)

    def import_payload(self, payload):
        return self._call("import", "POST", "/session/import", payload)

    def stats(self):
        return self._call("stats", "GET", "/stats")

    def sync_prior(self, pool_snap=None):
        body = {} if pool_snap is None else {"pool": pool_snap}
        return self._call("prior_sync", "POST", "/prior/sync", body)

    def healthz(self):
        try:
            return self._call("healthz", "GET", "/healthz")
        except ReplicaUnavailable:
            # breaker/budget fast-fail is TRANSPORT state, not the
            # replica answering unready — let check_health report (and
            # evict) it as breaker_open, distinctly
            raise
        except SlabFull:
            # a 503 here is the replica saying "unready" — report it as
            # the healthz body would
            return {"ok": False, "ready": False, "status": "unready",
                    "draining": False, "problems": ["unready"]}

    def has_session(self, sid) -> bool:
        try:
            self.best(sid)
            return True
        except UnknownSession:
            return False
        except (SlabFull, BucketQuarantined):
            return True  # restoring/healing/migrating: it exists

    def session_epoch(self, sid) -> Optional[int]:
        try:
            out = self._call("epoch", "GET", f"/session/{sid}/epoch")
            return int(out.get("epoch") or 0)
        except UnknownSession:
            return None
        except (SlabFull, BucketQuarantined):
            return None  # exists but unreadable right now

    def open_sids(self) -> list[str]:
        return list((self._call("sessions", "GET", "/sessions") or {})
                    .get("sessions", []))

    def open_count(self) -> int:
        st = self.stats()
        return int(st.get("open_sessions") or 0)

    def export_for_migration(self, sid) -> dict:
        # the PREPARE half of the hold protocol (see InprocReplica)
        return self.export(sid, close=False, hold=True)


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class SessionRouter:
    """The fleet's front door (duck-types ServeApp's verb surface, so
    ``make_server(router, port)`` serves it over the same asyncio HTTP
    stack a single replica uses).

    Construction takes ``{replica_id: handle}``; :meth:`start` begins
    health polling. Topology changes (eviction, rejoin,
    :meth:`add_replica` / :meth:`remove_replica`) trigger
    :meth:`rebalance` — drain-and-migrate of exactly the minimal re-owned
    key range."""

    def __init__(self, replicas: Optional[dict] = None, telemetry=None,
                 auto_rebalance: bool = True,
                 journal_path: Optional[str] = None,
                 faults=None, health_hysteresis: int = 2,
                 tracing: bool = True,
                 slo_fast_s: float = 300.0, slo_slow_s: float = 3600.0,
                 slo_store=None):
        from concurrent.futures import ThreadPoolExecutor

        from coda_tpu.serve.metrics import ServeMetrics
        from coda_tpu.telemetry import Telemetry
        from coda_tpu.telemetry.slo import SloSweeper, default_fleet_slos

        self._lock = threading.RLock()
        self.replicas: dict[str, object] = dict(replicas or {})
        self._routable: set[str] = set(self.replicas)
        self._health: dict[str, str] = {rid: "ok" for rid in self.replicas}
        # deliberate off-owner placements (peer paging, mid-rebalance):
        # sid -> replica id; consulted before the HRW owner
        self._placed: dict[str, str] = {}
        # ownership epochs: sid -> the epoch of the CURRENT owner's copy
        # (bumped per migration/peer-page, stamped on every routed verb
        # so a stale copy fences itself; the journal's committed records
        # are the durable half — recover_from_journal rebuilds this)
        self._epochs: dict[str, int] = {}
        # operator-evicted replicas the health poller must NOT re-admit
        # (a draining replica's /healthz still answers ok until it
        # stops; rejoin() lifts the cordon explicitly)
        self._cordoned: set[str] = set()
        # per-sid migration gates: verbs wait these out, then re-locate
        self._migrating: dict[str, threading.Event] = {}
        # health hysteresis: consecutive same-direction probe outcomes
        # required before a membership change (a single flapping probe
        # must not churn the HRW keyspace); rid -> (direction, streak)
        self.health_hysteresis = max(1, int(health_hysteresis))
        self._streaks: dict[str, tuple] = {}
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.metrics = ServeMetrics()   # router-level request accounting
        self.draining = False
        self.auto_rebalance = auto_rebalance
        # per-edge fault injection (serve/faults.py net_* names) — shared
        # with every handle's transport by add_replica
        self.faults = faults
        # Fleet installs this: kill_hook(rid) SIGKILLs a replica (the
        # kill_replica fault's applier)
        self.kill_hook = None
        self.counters = {
            "requests_routed": 0, "reroutes": 0, "migrations": 0,
            "migration_failures": 0, "evictions": 0, "rejoins": 0,
            "rebalances": 0, "peer_pages": 0, "sessions_dropped": 0,
            "fencing_rejections": 0, "fence_failures": 0,
            "journal_replays": 0, "migrations_in_doubt": 0,
            "prior_syncs": 0, "prior_deltas_merged": 0,
        }
        # the fleet's merged surrogate-prior pool (serve/priors.py),
        # created lazily on the first replica delta; exchange rides the
        # health poll — see check_health
        self.prior_pool = None
        self._prior_unsupported: set[str] = set()
        self.migrations_via: dict[str, int] = {}   # snapshot vs replay
        self.routed_to: dict[str, int] = {rid: 0 for rid in self.replicas}
        self._executor = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="router-verb")
        self._running = False
        self._poll_thread: Optional[threading.Thread] = None
        self._wakeup = threading.Event()
        self.ready = threading.Event()
        if self.replicas:
            self.ready.set()
        # the span vocabulary the trace-based attribution keys on
        self._spans = self.telemetry.spans
        # distributed tracing: the router is the fleet's front door, so
        # it MINTS the trace context when the client didn't send one —
        # every label decision gets exactly one causal trace. Purely
        # observational (spans + retention), never read by routing.
        self.tracing = bool(tracing)
        # adopted trace payloads: a replica about to be rebuilt (rolling
        # restart) hands its retained per-trace spans to the router so
        # traces survive the restart — trace_id -> [wire payloads].
        # Bounded FIFO like the recorders' own retention; a crash-killed
        # replica hands off nothing (its spans are honestly lost).
        self._adopted_traces: "collections.OrderedDict[str, list]" = \
            collections.OrderedDict()
        self._adopted_capacity = 4096
        # the SLO watchtower: declarative objectives evaluated against
        # the aggregated fleet snapshot on the health-poll thread;
        # multi-window burn rates, typed fire/clear alerts (flushed to
        # ``slo_store`` when given), coda_slo_* gauges on /metrics
        # availability/latency/recovery objectives PLUS the decision-
        # quality plane's (shadow-audit divergence, calibration ECE,
        # drift firing) — quality probes read each replica snapshot's
        # "quality" section and report no-data when --no-quality hid it
        from coda_tpu.telemetry.quality import quality_slos

        self.slo = SloSweeper(default_fleet_slos() + quality_slos(),
                              registry=self.telemetry.registry,
                              store=slo_store,
                              fast_s=slo_fast_s, slow_s=slo_slow_s)
        # the migration journal: crash-consistent move records (intent/
        # exported/imported/committed), replayed by recover_from_journal
        self.journal = None
        if journal_path is not None:
            from coda_tpu.serve.journal import MigrationJournal

            self.journal = MigrationJournal(journal_path)
            # the committed records are the durable epoch map: a
            # restarted router must stamp verbs at least as new as the
            # last committed move or a stale copy could serve again
            for sid, rec in self.journal.committed().items():
                self._epochs[sid] = rec["epoch"]
        for rid in self.replicas:
            self._wire_handle(self.replicas[rid])

    def _wire_handle(self, handle) -> None:
        """Share the router's fault injector + span recorder with a
        handle's transport (one fault domain, one trace vocabulary)."""
        t = getattr(handle, "transport", None)
        if t is not None:
            if t.faults is None:
                t.faults = self.faults
            t.spans = self._spans

    # -- topology ----------------------------------------------------------
    def add_replica(self, replica_id: str, handle, rebalance: bool = True
                    ) -> None:
        self._wire_handle(handle)
        with self._lock:
            self.replicas[replica_id] = handle
            self._routable.add(replica_id)
            self._health[replica_id] = "ok"
            self._streaks.pop(replica_id, None)
            self.routed_to.setdefault(replica_id, 0)
            self.ready.set()
        if rebalance:
            self.rebalance()

    def remove_replica(self, replica_id: str, migrate: bool = True) -> dict:
        """Drain one replica out of the fleet: evict it from routing,
        migrate every session it holds to the sessions' new HRW owners
        (each digest-verified), then forget the handle. Returns the
        migration report."""
        with self._lock:
            if replica_id not in self.replicas:
                return {"migrated": 0}
            self._routable.discard(replica_id)
        report = (self._migrate_all_off(replica_id) if migrate
                  else {"migrated": 0})
        with self._lock:
            self.replicas.pop(replica_id, None)
            self._health.pop(replica_id, None)
        return report

    def evict(self, replica_id: str, cordon: bool = False) -> None:
        """Take a replica out of routing without forgetting it (health
        eviction: it may recover and rejoin). ``cordon`` additionally
        bars the health poller from re-admitting it — the drain flow,
        where the replica's /healthz keeps answering ok until it
        actually stops."""
        with self._lock:
            if cordon:
                self._cordoned.add(replica_id)
            if replica_id in self._routable:
                self._routable.discard(replica_id)
                self.counters["evictions"] += 1

    def rejoin(self, replica_id: str) -> None:
        with self._lock:
            self._cordoned.discard(replica_id)
            if replica_id in self.replicas and \
                    replica_id not in self._routable:
                self._routable.add(replica_id)
                self.counters["rejoins"] += 1

    def routable(self) -> list[str]:
        with self._lock:
            return sorted(self._routable)

    def owner_of(self, sid: str) -> str:
        return rendezvous_owner(sid, self.routable())

    # -- health ------------------------------------------------------------
    def check_health(self) -> dict:
        """One poll of every replica's /healthz: unreachable or unready
        replicas leave the routing set, recovered ones rejoin — but only
        after ``health_hysteresis`` CONSECUTIVE same-direction outcomes
        (a single flapping probe must not churn the HRW keyspace and
        trigger needless migrations). A replica whose transport breaker
        is open is reported (and evicted) as ``breaker_open`` — distinct
        from health eviction on ``/stats``; the breaker's half-open
        window makes this same poll the recovery probe. Returns
        {replica: status}; topology changes trigger a rebalance when
        ``auto_rebalance``."""
        statuses: dict[str, str] = {}
        with self._lock:
            items = list(self.replicas.items())
        changed = False
        for rid, handle in items:
            breaker = getattr(getattr(handle, "transport", None),
                              "breaker", None)
            if breaker is not None and breaker.state == "open":
                # fail fast: K consecutive transport failures already ARE
                # the hysteresis — don't burn a probe the breaker would
                # refuse anyway
                status = "breaker_open"
            else:
                try:
                    hz = handle.healthz()
                    status = hz.get("status") or (
                        "ok" if hz.get("ready") else "unready")
                    if hz.get("draining"):
                        status = "draining"
                except ReplicaUnavailable:
                    status = "breaker_open"
                except Exception:
                    status = "unreachable"
            statuses[rid] = status
            if status in ("ok", "degraded"):
                self._sync_prior_with(rid, handle)
            routable = status in ("ok", "degraded")
            with self._lock:
                was = rid in self._routable
                cordoned = rid in self._cordoned
                self._health[rid] = status
                if routable == was:
                    self._streaks.pop(rid, None)
                    flip = False
                else:
                    d, n = self._streaks.get(rid, (routable, 0))
                    n = n + 1 if d == routable else 1
                    self._streaks[rid] = (routable, n)
                    # a breaker-open edge needs no further confirmation:
                    # the K consecutive failures that tripped it are the
                    # hysteresis
                    flip = n >= self.health_hysteresis or \
                        status == "breaker_open"
            if not flip:
                continue
            with self._lock:
                self._streaks.pop(rid, None)
            if routable and not cordoned:
                self.rejoin(rid)
                changed = True
            elif not routable:
                self.evict(rid)
                changed = True
        if changed and self.auto_rebalance:
            try:
                self.rebalance()
            except Exception:
                pass  # the poller must survive a mid-rebalance hiccup
        return statuses

    def _sync_prior_with(self, rid: str, handle) -> None:
        """The prior-pool exchange piggybacked on one healthy probe:
        push the router's merged pool, fold the replica's drained delta
        back in. Never fails the poll — a replica that doesn't speak the
        verb (older build, pool off) is remembered and skipped."""
        if rid in self._prior_unsupported:
            return
        sync = getattr(handle, "sync_prior", None)
        if sync is None:
            self._prior_unsupported.add(rid)
            return
        try:
            snap = (self.prior_pool.snapshot()
                    if self.prior_pool is not None else None)
            delta = (sync(snap) or {}).get("delta") or {}
        except (ConnectionError, OSError, TimeoutError,
                ReplicaUnavailable):
            return  # transport trouble: the delta stays queued replica-
            #         side (drain happens inside a successful call only)
        except Exception:
            # an app-level rejection (404 on an old server): permanent
            self._prior_unsupported.add(rid)
            return
        with self._lock:
            self.counters["prior_syncs"] += 1
        if not delta:
            return
        if self.prior_pool is None:
            from coda_tpu.serve.priors import PriorPool

            with self._lock:
                if self.prior_pool is None:
                    self.prior_pool = PriorPool()
        n = self.prior_pool.merge_delta(delta)
        if n:
            with self._lock:
                self.counters["prior_deltas_merged"] += n

    def start(self, poll_s: float = 0.25) -> "SessionRouter":
        if self._poll_thread is not None:
            return self
        self._running = True

        def _loop():
            ticks = 0
            while self._running:
                try:
                    self.check_health()
                except Exception:
                    pass
                ticks += 1
                if ticks % 4 == 0:
                    # SLO sweep at 1/4 the health cadence: stats() fans
                    # out to every replica, so it rides a slower beat
                    # than the cheap healthz probes
                    try:
                        self.slo.observe(self.stats())
                    except Exception:
                        pass  # the poller survives a mid-sweep hiccup
                self._wakeup.wait(poll_s)
                self._wakeup.clear()

        self._poll_thread = threading.Thread(
            target=_loop, daemon=True, name="router-health")
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        self._wakeup.set()
        t, self._poll_thread = self._poll_thread, None
        if t is not None:
            t.join(timeout=5.0)

    def drain(self, timeout: float = 30.0) -> None:
        self.draining = True
        self.stop()
        self._executor.shutdown(wait=False)
        if self.journal is not None:
            self.journal.close()

    # -- location ----------------------------------------------------------
    def _locate(self, sid: str) -> str:
        gate = None
        with self._lock:
            gate = self._migrating.get(sid)
        if gate is not None:
            gate.wait(MIGRATION_WAIT_S)
        with self._lock:
            rid = self._placed.get(sid)
            if rid is not None and rid in self.replicas:
                # an off-owner placement on an evicted-but-known replica
                # still resolves: it serves its sessions while draining
                return rid
            routable = sorted(self._routable)
        return rendezvous_owner(sid, routable)

    def _find(self, sid: str, exclude=()) -> tuple:
        """Search the fleet for a session that is not where the shard map
        says (a topology change the rebalance has not caught up with).
        ALL known replicas are probed — an evicted-but-draining replica
        still serves its existing sessions until they migrate off it —
        in rendezvous-rank order, the most likely ex-owners first.
        Returns ``(replica_id_or_None, n_unreachable)``: a failed find
        with unreachable probes is NOT proof of absence — the session
        may live behind a partition, and the caller must answer
        retryable, not 404."""
        with self._lock:
            candidates = [r for r in self.replicas if r not in exclude]
        unreachable = 0
        for rid in rendezvous_rank(sid, candidates):
            try:
                if self.replicas[rid].has_session(sid):
                    return rid, unreachable
            except Exception:
                unreachable += 1
                continue
        return None, unreachable

    def _trace_root(self, trace_ctx):
        """The fleet front door's trace context: continue the client's,
        or MINT one (tracing on) so every label decision has a causal
        trace even from untraced clients. None when tracing is off."""
        if not self.tracing:
            return None
        if trace_ctx is not None:
            return trace_ctx.child()
        from coda_tpu.telemetry.trace import mint

        return mint()

    def _forward(self, verb: str, sid: str, fn, trace_ctx=None):
        """Route one verb: locate -> dispatch (with the route span
        nesting the replica dispatch span, the router's epoch stamped on
        the call) -> on UnknownSession, search the fleet and re-route
        once; on a StaleOwner fencing rejection, the answering replica
        holds a pre-migration copy — exclude it and re-locate; on a dead
        replica (or an open breaker), evict and fail over.

        ``fn(handle, epoch, trace)`` gets a per-dispatch child context —
        each failover attempt carries its own span, so a request retried
        across a migration leaves BOTH replicas' lanes in one trace."""
        ctx = self._trace_root(trace_ctx)
        with self._spans.span(f"route/{verb}", lane="host:router",
                              **(ctx.attrs() if ctx is not None else {})):
            last_err: Optional[BaseException] = None
            stale: set = set()
            for attempt in range(4):
                rid = self._locate(sid)
                with self._lock:
                    handle = self.replicas.get(rid)
                    epoch = self._epochs.get(sid)
                if handle is None:
                    continue
                dctx = ctx.child() if ctx is not None else None
                dattrs = dict(dctx.attrs(), replica=rid) \
                    if dctx is not None else {}
                try:
                    with self._spans.span(f"dispatch/{rid}",
                                          lane="host:router", **dattrs):
                        out = fn(handle, epoch, dctx)
                    with self._lock:
                        self.counters["requests_routed"] += 1
                        self.routed_to[rid] = \
                            self.routed_to.get(rid, 0) + 1
                    return out
                except StaleOwner as e:
                    # the fence held: rid serves a pre-migration copy
                    # (healed partition / crash-restored unsealed
                    # stream). Never commit there — find the copy whose
                    # epoch matches the stamp and re-route.
                    last_err = e
                    stale.add(rid)
                    with self._lock:
                        self.counters["fencing_rejections"] += 1
                        if self._placed.get(sid) == rid:
                            self._placed.pop(sid, None)
                    found, unreachable = self._find(sid, exclude=stale)
                    if found is None:
                        if unreachable:
                            raise ReplicaUnavailable(
                                f"session {sid}: current owner "
                                f"unreachable while re-locating after a "
                                f"fencing rejection ({unreachable} "
                                "replica(s) down); retry")
                        raise
                    with self._lock:
                        self._placed[sid] = found
                        self.counters["reroutes"] += 1
                except UnknownSession as e:
                    last_err = e
                    with self._lock:
                        gate = self._migrating.get(sid)
                    if gate is not None:
                        # we located the source BEFORE its migration gate
                        # went up and dispatched after the fence landed:
                        # mid-move the payload exists only in the
                        # migrating thread's hands, so neither side
                        # answers. Wait the move out, then re-locate —
                        # never a 404 for a session that is merely in
                        # transit.
                        gate.wait(MIGRATION_WAIT_S)
                        continue
                    found, unreachable = self._find(sid,
                                                    exclude=stale | {rid})
                    if found is None:
                        if unreachable:
                            # an unreachable replica may HOLD the
                            # session: absence is unproven, so the
                            # answer is retryable backpressure (503),
                            # never a 404 for a session a partition is
                            # merely hiding
                            raise ReplicaUnavailable(
                                f"session {sid}: not found on reachable "
                                f"replicas and {unreachable} replica(s) "
                                "unreachable; retry after the fleet "
                                "heals") from e
                        if attempt < 3:
                            # a migration's gate may have been popped
                            # between our dispatch and the check above —
                            # one short beat, then re-locate
                            time.sleep(0.01)
                            continue
                        raise
                    with self._lock:
                        self._placed[sid] = found
                        self.counters["reroutes"] += 1
                except ReplicaUnavailable as e:
                    # breaker open / retry budget gone: the edge is
                    # black-holed — evict and fail over like a dead host
                    last_err = e
                    self.evict(rid)
                except (ConnectionError, OSError) as e:
                    # replica went away under us: evict, let health/
                    # rebalance recover it, and fail over this request
                    last_err = e
                    self.evict(rid)
            raise (last_err or SlabFull("no routable replica answered"))

    # -- the front-door verb surface (ServeApp-compatible) -----------------
    def open_session(self, task: Optional[str] = None,
                     seed: Optional[int] = None, trace_ctx=None) -> dict:
        if self.draining:
            from coda_tpu.serve.server import Draining

            raise Draining()
        # the router mints the sid so placement is HRW on the id BEFORE
        # the replica admits it (the replica honors the pinned id)
        sid = uuid.uuid4().hex
        ctx = self._trace_root(trace_ctx)
        with self._spans.span("route/open", lane="host:router",
                              **(ctx.attrs() if ctx is not None else {})):
            last_err: Optional[BaseException] = None
            for _ in range(3):
                owner = rendezvous_owner(sid, self.routable())
                with self._lock:
                    handle = self.replicas.get(owner)
                if handle is None:
                    continue  # removed between routable() and lookup
                dctx = ctx.child() if ctx is not None else None
                dattrs = dict(dctx.attrs(), replica=owner) \
                    if dctx is not None else {}
                try:
                    with self._spans.span(f"dispatch/{owner}",
                                          lane="host:router", **dattrs):
                        out = handle.open(task=task, seed=seed, sid=sid,
                                          trace=dctx)
                except (ConnectionError, OSError) as e:
                    # dead owner inside the health-poll window: evict it
                    # (like every _forward verb does) and re-own the sid
                    # over the survivors instead of bouncing the client
                    last_err = e
                    self.evict(owner)
                    continue
                with self._lock:
                    self.counters["requests_routed"] += 1
                    self.routed_to[owner] = \
                        self.routed_to.get(owner, 0) + 1
                return out
            raise (last_err or SlabFull("no routable replica answered"))

    async def open_session_async(self, task=None, seed=None,
                                 trace_ctx=None) -> dict:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: self.open_session(task, seed, trace_ctx=trace_ctx))

    def label(self, sid: str, label, idx=None, request_id=None,
              epoch=None, trace_ctx=None) -> dict:
        # ``epoch`` is accepted for surface parity with ServeApp (the
        # shared front door); the ROUTER's own epoch map is what gets
        # stamped on the replica call — that map is the fence.
        return self._forward(
            "label", sid,
            lambda h, e, t: h.label(sid, label, idx=idx,
                                    request_id=request_id, epoch=e,
                                    trace=t),
            trace_ctx=trace_ctx)

    async def label_async(self, sid, label, idx=None,
                          request_id=None, epoch=None,
                          trace_ctx=None) -> dict:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: self.label(sid, label, idx=idx, request_id=request_id,
                               trace_ctx=trace_ctx))

    def labels(self, sid: str, labels, idx=None, request_id=None,
               epoch=None, trace_ctx=None) -> dict:
        return self._forward(
            "labels", sid,
            lambda h, e, t: h.labels(sid, labels, idx=idx,
                                     request_id=request_id, epoch=e,
                                     trace=t),
            trace_ctx=trace_ctx)

    async def labels_async(self, sid, labels, idx=None,
                           request_id=None, epoch=None,
                           trace_ctx=None) -> dict:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: self.labels(sid, labels, idx=idx,
                                request_id=request_id,
                                trace_ctx=trace_ctx))

    def best(self, sid: str, epoch=None, trace_ctx=None) -> dict:
        return self._forward("best", sid,
                             lambda h, e, t: h.best(sid, epoch=e, trace=t),
                             trace_ctx=trace_ctx)

    def trace(self, sid: str, epoch=None) -> dict:
        return self._forward("trace", sid,
                             lambda h, e, t: h.trace(sid, epoch=e))

    def close_session(self, sid: str, epoch=None) -> dict:
        out = self._forward("close", sid,
                            lambda h, e, t: h.close(sid, epoch=e))
        with self._lock:
            self._placed.pop(sid, None)
            self._epochs.pop(sid, None)
        return out

    def export_session(self, sid: str, close: bool = False,
                       hold: bool = False) -> dict:
        out = self._forward("export", sid,
                            lambda h, e, t: h.export(sid, close=close,
                                                     hold=hold))
        if close:
            with self._lock:
                self._placed.pop(sid, None)
                self._epochs.pop(sid, None)
        return out

    def end_migration(self, sid: str, drop: bool = False) -> dict:
        # router-mediated fence (surface parity with ServeApp)
        return self._forward("fence", sid,
                             lambda h, e, t: h.fence(sid, drop=drop))

    def session_epoch(self, sid: str) -> dict:
        """Front-door twin of ``ServeApp.session_epoch``: the router's
        own epoch map answers when it has an entry (it is the fence's
        authority); otherwise the located replica's copy does."""
        with self._lock:
            ep = self._epochs.get(sid)
        if ep is not None:
            return {"session": sid, "epoch": int(ep)}
        rid = self._locate(sid)
        with self._lock:
            handle = self.replicas.get(rid)
        e = handle.session_epoch(sid) if handle is not None else None
        if e is None:
            raise UnknownSession(sid)
        return {"session": sid, "epoch": int(e)}

    def import_session(self, payload: dict) -> dict:
        if self.draining:
            from coda_tpu.serve.server import Draining

            raise Draining()
        sid = str(payload.get("session") or "")
        owner = rendezvous_owner(sid, self.routable())
        with self._spans.span("route/import", lane="host:router"):
            with self._lock:
                handle = self.replicas[owner]
            with self._spans.span(f"dispatch/{owner}", lane="host:router"):
                return handle.import_payload(payload)

    # -- migration ---------------------------------------------------------
    def _commit_migration(self, sid: str, src, src_rid: str, dst_rid: str,
                          epoch_next: int, via: str, mid) -> dict:
        """The commit half of a move whose import landed: fence the
        source copy (best-effort — a fence the partition eats leaves a
        STALE copy behind, which the epoch stamp defends until recovery
        re-fences it), adopt the epoch + placement, count, journal."""
        fenced = True
        try:
            src.fence(sid, drop=True)
        except UnknownSession:
            pass
        except Exception:
            fenced = False
            with self._lock:
                self.counters["fence_failures"] += 1
        with self._lock:
            self._epochs[sid] = epoch_next
            # home placement needs no override; an off-owner
            # destination (peer paging) keeps one
            owner = rendezvous_owner(sid, sorted(self._routable))
            if dst_rid == owner:
                self._placed.pop(sid, None)
            else:
                self._placed[sid] = dst_rid
            self.counters["migrations"] += 1
            self.migrations_via[via] = \
                self.migrations_via.get(via, 0) + 1
        if mid is not None:
            self.journal.record(mid, "committed", epoch=epoch_next,
                                fenced=fenced)
        info = {"migrated": sid, "from": src_rid, "to": dst_rid,
                "via": via, "epoch": epoch_next}
        if not fenced:
            info["fence_pending"] = True
        return info

    def _kill_point(self, src_rid: str, dst_rid: str) -> None:
        """The seeded mid-migration process-fault site: between the
        export and the import, ``kill_replica`` (edge-addressed) fires
        the fleet's kill hook — SIGKILL semantics for whichever end the
        fault spec names."""
        if self.faults is None or self.kill_hook is None:
            return
        for rid in (src_rid, dst_rid):
            if "kill_replica" in self.faults.fire("migrate_mid", edge=rid):
                self.kill_hook(rid)

    def migrate_session(self, sid: str, src_rid: str, dst_rid: str) -> dict:
        """Move one session with the journaled prepare/commit protocol:

          1. journal ``intent`` (src, dst, the bumped epoch);
          2. PREPARE on the source (quiesce + hold + export WITHOUT
             close — the source keeps a recoverable, uncommittable
             copy); journal ``exported`` with the payload digest;
          3. import on the destination at the bumped ownership epoch
             (digest-verified snapshot or bitwise stream replay);
             journal ``imported``;
          4. FENCE the source (drop its copy, seal its stream), commit
             the router's epoch/placement maps, journal ``committed``.

        A crash — of the router or either replica — between any two
        steps degrades to *didn't move* (the source's held copy resumes
        on abort or journal recovery) or *moved exactly once* (journal
        recovery finalizes the fence); and even an unfenced stale copy
        can never commit a label, because every routed verb carries the
        bumped epoch the stale copy fails. On an import failure the
        source is un-held and the session resumes there — never gone."""
        gate = threading.Event()
        with self._lock:
            if self._migrating.get(sid) is not None:
                return {"skipped": "already migrating"}
            self._migrating[sid] = gate
            src = self.replicas.get(src_rid)
            dst = self.replicas.get(dst_rid)
            epoch_next = self._epochs.get(sid, 0) + 1
        info: dict = {}
        mid = None
        try:
            if src is None or dst is None:
                return {"skipped": "replica gone"}
            if self.journal is not None:
                mid = self.journal.begin(sid, src_rid, dst_rid, epoch_next)
            try:
                payload = src.export_for_migration(sid)
            except UnknownSession:
                if mid is not None:
                    self.journal.record(mid, "aborted", reason="closed")
                return {"skipped": "closed"}
            # the ownership bump happens HERE, under the router's hand:
            # demote/wake round trips preserve the epoch, only a
            # committed move advances it
            payload = dict(payload)
            payload["epoch"] = epoch_next
            if mid is not None:
                from coda_tpu.serve.journal import payload_digest

                self.journal.record(mid, "exported",
                                    digest=payload_digest(payload),
                                    n_labeled=payload.get("n_labeled"))
            self._kill_point(src_rid, dst_rid)
            try:
                res = None
                for i in range(8):
                    try:
                        res = dst.import_payload(payload)
                        break
                    except SlabFull as e:
                        # transient admission pressure on the peer
                        # (every slot momentarily pinned): a migration
                        # must out-wait it, not fail the move — but a
                        # black-holed edge (breaker open) fails NOW
                        if isinstance(e, ReplicaUnavailable) or i == 7:
                            raise
                        time.sleep(0.01 * (i + 1))
                if mid is not None:
                    self.journal.record(mid, "imported")
                info = self._commit_migration(
                    sid, src, src_rid, dst_rid, epoch_next,
                    res.get("restored_via", "?"), mid)
            except BaseException as e:
                # before restoring the source, probe the destination: a
                # lost import RESPONSE is not a lost import — if the
                # copy landed at the bumped epoch, the move COMMITTED
                # and must finalize, or two live copies would serve
                # under one sid
                landed = False
                try:
                    ep = dst.session_epoch(sid)
                    landed = ep is not None and ep >= epoch_next
                except Exception:
                    landed = False
                if landed:
                    if mid is not None:
                        self.journal.record(mid, "imported",
                                            ack_lost=True)
                    info = self._commit_migration(
                        sid, src, src_rid, dst_rid, epoch_next,
                        "recovered", mid)
                    return info
                # the import never landed (or was refused): the source
                # still holds the session — lift the hold and the move
                # degrades to "didn't move", never to "gone"
                with self._lock:
                    self.counters["migration_failures"] += 1
                try:
                    src.fence(sid, drop=False)
                    with self._lock:
                        self._placed[sid] = src_rid
                except BaseException:
                    # even the abort couldn't reach the source: its held
                    # copy stays parked (and crash restore resurrects it
                    # from the unsealed stream). Leave the journal at its
                    # last NON-terminal phase so recover_from_journal
                    # resolves the doubt — recording 'aborted' here would
                    # terminally hide a move recovery must still settle.
                    with self._lock:
                        self.counters["migrations_in_doubt"] += 1
                    info = {"failed": sid, "error": repr(e),
                            "in_doubt": True}
                    return info
                if mid is not None:
                    self.journal.record(mid, "aborted", reason=repr(e))
                info = {"failed": sid, "error": repr(e)}
            return info
        finally:
            with self._lock:
                self._migrating.pop(sid, None)
            gate.set()

    def recover_from_journal(self) -> dict:
        """Resolve every in-doubt migration after a router restart (call
        once the replicas are registered, before serving): probe the
        destination for the journaled copy — present at the bumped epoch
        means the move committed on the target, so FINALIZE (fence the
        source, adopt epoch + placement); absent means the import never
        landed, so RESTORE (lift the source's hold; its copy — or its
        crash-restored stream — serves again). Either way each in-doubt
        SIGKILL window degrades to didn't-move or moved-exactly-once."""
        if self.journal is None:
            return {"resolved": 0}
        report: dict = {"resolved": 0, "finalized": [], "restored": [],
                       "in_doubt": []}
        for move in self.journal.in_doubt():
            sid = move.get("sid")
            mid = move.get("mid")
            epoch = int(move.get("epoch") or 0)
            with self._lock:
                src = self.replicas.get(move.get("src"))
                dst = self.replicas.get(move.get("dst"))
            on_dst = False
            if dst is not None:
                try:
                    ep = dst.session_epoch(sid)
                    on_dst = ep is not None and ep >= epoch
                except Exception:
                    on_dst = False
            with self._lock:
                self.counters["journal_replays"] += 1
            if on_dst:
                fenced = True
                if src is not None:
                    try:
                        src.fence(sid, drop=True)
                    except UnknownSession:
                        pass
                    except Exception:
                        fenced = False
                        with self._lock:
                            self.counters["fence_failures"] += 1
                with self._lock:
                    self._epochs[sid] = max(self._epochs.get(sid, 0),
                                            epoch)
                    routable = sorted(self._routable)
                    if routable and rendezvous_owner(
                            sid, routable) == move.get("dst"):
                        self._placed.pop(sid, None)
                    else:
                        self._placed[sid] = move.get("dst")
                self.journal.record(mid, "committed", epoch=epoch,
                                    fenced=fenced, replayed=True)
                report["finalized"].append(sid)
            else:
                restored = False
                if src is not None:
                    try:
                        src.fence(sid, drop=False)  # lift any hold
                        restored = src.has_session(sid)
                    except Exception:
                        restored = False
                self.journal.record(mid, "aborted",
                                    reason="journal recovery: import "
                                           "never landed", replayed=True)
                if restored:
                    report["restored"].append(sid)
                else:
                    # neither end answers for it right now — the source's
                    # crash restore (its stream is unsealed) resurrects
                    # it; record the doubt attributably
                    report["in_doubt"].append(sid)
            report["resolved"] += 1
        return report

    def _migrate_all_off(self, src_rid: str) -> dict:
        """Drain-and-migrate every session off one replica to the
        sessions' HRW owners over the remaining routable set."""
        with self._lock:
            handle = self.replicas.get(src_rid)
            routable = sorted(self._routable - {src_rid})
        if handle is None or not routable:
            return {"migrated": 0}
        moved = failed = 0
        fail_errors: list = []
        for sid in handle.open_sids():
            dst = rendezvous_owner(sid, routable)
            info = self.migrate_session(sid, src_rid, dst)
            if "migrated" in info:
                moved += 1
            elif "failed" in info:
                failed += 1
                fail_errors.append(info.get("error"))
        out = {"migrated": moved, "failed": failed}
        if fail_errors:
            out["errors"] = fail_errors[:10]
        return out

    def rebalance(self, full: bool = False) -> dict:
        """Move every session to its HRW owner over the CURRENT routable
        set — after a topology change this is exactly the minimal
        re-owned key range (sessions whose owner is unchanged never
        move). ``full=True`` also re-homes deliberate off-owner
        placements (peer-paged sessions); the default leaves them where
        the pressure balancing put them."""
        moved = failed = 0
        fail_errors: list = []
        with self._lock:
            items = [(rid, self.replicas[rid])
                     for rid in sorted(self._routable)]
            routable = sorted(self._routable)
            placed = dict(self._placed)
        for rid, handle in items:
            try:
                sids = handle.open_sids()
            except Exception:
                continue
            for sid in sids:
                if not full and placed.get(sid) == rid:
                    continue  # deliberately placed here (peer paging)
                owner = rendezvous_owner(sid, routable)
                if owner == rid:
                    continue
                info = self.migrate_session(sid, rid, owner)
                if "migrated" in info:
                    moved += 1
                elif "failed" in info:
                    failed += 1
                    fail_errors.append(info.get("error"))
        with self._lock:
            self.counters["rebalances"] += 1
        out = {"moved": moved, "failed": failed}
        if fail_errors:
            out["errors"] = fail_errors[:10]
        return out

    def list_sessions(self) -> dict:
        """Union of every replica's addressable sessions (GET /sessions
        on the router — the fleet-wide worklist)."""
        out: list[str] = []
        seen: set = set()
        with self._lock:
            items = list(self.replicas.items())
        for rid, handle in items:
            try:
                fresh = [s for s in handle.open_sids() if s not in seen]
            except Exception:
                continue
            out += fresh
            seen.update(fresh)
        return {"sessions": out}

    # -- observability -----------------------------------------------------
    def healthz(self) -> dict:
        with self._lock:
            health = dict(self._health)
            routable = sorted(self._routable)
            n_replicas = len(self.replicas)
        ready = bool(routable) and not self.draining
        problems = [f"replica_{rid}_{st}" for rid, st in sorted(
            health.items()) if st not in ("ok",)]
        status = ("unready" if not routable
                  else "degraded" if len(routable) < n_replicas or problems
                  else "ok")
        return {"ok": ready, "ready": bool(routable),
                "draining": self.draining, "status": status,
                "role": "router", "replicas": health,
                "routable": routable, "problems": problems}

    def stats(self) -> dict:
        """The merged fleet snapshot: per-replica /stats sections, the
        aggregate sums a dashboard wants, and the router's own routing/
        migration counters — one endpoint, not a per-replica curl loop."""
        with self._lock:
            items = list(self.replicas.items())
            counters = dict(self.counters)
            via = dict(self.migrations_via)
            routed = dict(self.routed_to)
            routable = sorted(self._routable)
            health = dict(self._health)
            placed = len(self._placed)
            epochs = len(self._epochs)
        per_replica: dict[str, dict] = {}
        transports: dict[str, dict] = {}
        for rid, handle in items:
            t = getattr(handle, "transport", None)
            if t is not None:
                transports[rid] = t.snapshot()
            try:
                per_replica[rid] = handle.stats()
            except Exception as e:
                per_replica[rid] = {"error": repr(e)}
        agg_keys = ("open_sessions", "slab_occupancy", "dispatches",
                    "requests", "sessions_opened", "sessions_closed",
                    "demotions", "wakes", "hibernates", "peer_pages")
        aggregate = {k: sum(int(s.get(k) or 0) for s in per_replica.values()
                            if "error" not in s) for k in agg_keys}
        # breaker-open vs health-evicted, reported DISTINCTLY: the
        # breakers section is transport state, the health map is probe
        # state — an operator can tell a black-holed edge from an
        # unready process at a glance
        breakers = {rid: {"state": t["breaker_state"],
                          "trips": t["breaker_trips"],
                          "consecutive_failures":
                              t["consecutive_failures"]}
                    for rid, t in transports.items()}
        out = {
            "role": "router",
            "replicas": per_replica,
            "aggregate": aggregate,
            "router": {
                "routable": routable,
                "health": health,
                "health_hysteresis": self.health_hysteresis,
                "counters": counters,
                "migrations_via": via,
                "requests_to": routed,
                "placed_overrides": placed,
                "epoch_overrides": epochs,
                "migration_verified": sum(via.values()),
                "breakers": breakers,
                "transport": transports,
                "transport_retries": {
                    rid: t["retries_total"]
                    for rid, t in transports.items()},
            },
        }
        if self.journal is not None:
            out["router"]["journal"] = self.journal.stats()
        if self.prior_pool is not None:
            out["router"]["prior_pool"] = self.prior_pool.stats()
        return out

    def render_metrics(self) -> str:
        """The merged /metrics exposition: router registry families plus
        every serve family rendered ONCE with per-replica labels."""
        from coda_tpu.telemetry.prometheus import render_fleet

        st = self.stats()
        return render_fleet(st["replicas"],
                            registry=self.telemetry.registry,
                            router_stats=st["router"])

    def slo_snapshot(self) -> dict:
        """``GET /fleet/slo``: objectives, burn rates, firing state,
        recent alerts (the SLO watchtower's JSON face)."""
        return self.slo.snapshot()

    def quality_scorecard(self) -> dict:
        """``GET /fleet/quality`` at the fleet front door: each replica's
        decision-quality scorecard plus one fleet-level verdict (worst
        replica wins per organ — one diverged auditor grades the fleet
        diverged). Replicas running ``--no-quality`` are listed as
        disabled rather than silently dropped."""
        from coda_tpu.telemetry.quality import CALIBRATION_MIN_SAMPLES

        st = self.stats()
        per: dict[str, dict] = {}
        worst_ece = None
        any_audit = False
        diverged = firing = False
        for rid, snap in st["replicas"].items():
            if "error" in snap:
                per[rid] = {"error": snap["error"]}
                continue
            q = snap.get("quality")
            if not isinstance(q, dict):
                per[rid] = {"enabled": False}
                continue
            per[rid] = q
            audit = q.get("audit") or {}
            if audit.get("audits_total"):
                any_audit = True
                if (audit.get("divergences_recent") or 0) > 0:
                    diverged = True
            for cal in (q.get("calibration") or {}).values():
                ece = cal.get("ece")
                # same evidence floor as CalibrationMonitor.worst_ece:
                # thin per-replica buckets must not grade the fleet
                if (cal.get("n") or 0) < CALIBRATION_MIN_SAMPLES:
                    continue
                if ece is not None and (worst_ece is None
                                        or ece > worst_ece):
                    worst_ece = ece
            if any(d.get("firing")
                   for d in (q.get("drift") or {}).values()):
                firing = True
        return {
            "role": "router",
            "replicas": per,
            "verdict": {
                "calibration": ("no_data" if worst_ece is None else
                                ("ok" if worst_ece <= 0.25
                                 else "miscalibrated")),
                "worst_ece": worst_ece,
                "audit": ("diverged" if diverged
                          else ("ok" if any_audit else "no_data")),
                "drift": "firing" if firing else "ok",
            },
        }

    def adopt_trace_payloads(self, payloads: list) -> int:
        """Take custody of per-trace span payloads from a replica that is
        about to lose its recorder (rolling restart rebuilds the app):
        :meth:`collect_trace` keeps stitching these into the trace after
        the donor's in-memory rings are gone. Bounded FIFO per trace_id,
        same shape as :meth:`SpanRecorder.trace_payload`."""
        kept = 0
        with self._lock:
            for p in payloads or ():
                tid = (p or {}).get("trace_id")
                if not tid or not p.get("events"):
                    continue
                bucket = self._adopted_traces.get(tid)
                if bucket is None:
                    while len(self._adopted_traces) >= \
                            self._adopted_capacity:
                        self._adopted_traces.popitem(last=False)
                    bucket = []
                    self._adopted_traces[tid] = bucket
                bucket.append(p)
                kept += 1
        return kept

    @staticmethod
    def _merge_process_payloads(payloads: list) -> list:
        """Coalesce payloads sharing a process name (an adopted pre-restart
        payload plus the live replica's post-restart one) into ONE lane
        group, rebasing events onto the earliest payload's clock anchor so
        the stitched timeline stays aligned."""
        by_proc: dict = {}
        order = []
        for p in payloads:
            key = p.get("process") or ""
            if key not in by_proc:
                by_proc[key] = []
                order.append(key)
            by_proc[key].append(p)
        merged = []
        for key in order:
            group = by_proc[key]
            if len(group) == 1:
                merged.append(group[0])
                continue
            anchor = min(g["t0_unix"] for g in group)
            events = []
            for g in group:
                off = g["t0_unix"] - anchor
                for e in g["events"]:
                    events.append(dict(e, t0=e["t0"] + off,
                                       t1=e["t1"] + off))
            events.sort(key=lambda e: e["t0"])
            merged.append({"trace_id": group[0].get("trace_id"),
                           "process": key, "t0_unix": anchor,
                           "events": events})
        return merged

    def collect_trace(self, trace_id: str) -> dict:
        """``GET /trace/id/{id}`` at the fleet front door: stitch the
        router's own retained spans for one trace with every replica's
        (fetched over the normal verb transport — in-process or HTTP)
        plus any payloads adopted from restarted replicas, into ONE
        Chrome/Perfetto file with a process lane per member. A replica
        that can't answer contributes nothing rather than failing the
        stitch — a partial trace beats no trace."""
        from coda_tpu.telemetry.spans import stitch_traces

        tid = str(trace_id)
        payloads = [self.telemetry.spans.trace_payload(tid,
                                                       process="router")]
        with self._lock:
            items = sorted(self.replicas.items())
            payloads += [dict(p) for p in
                         self._adopted_traces.get(tid, ())]
        for rid, handle in items:
            fetch = getattr(handle, "trace_by_id", None)
            if fetch is None:
                continue
            try:
                p = fetch(tid)
            except Exception:
                continue
            if p and p.get("events"):
                p = dict(p)
                p["process"] = p.get("process") or str(rid)
                payloads.append(p)
        payloads = self._merge_process_payloads(
            [p for p in payloads if p.get("events")])
        out = stitch_traces(payloads)
        out["trace_id"] = tid
        # the per-process payload census: which fleet members retained
        # spans for this trace (the loadgen's completeness check reads
        # this instead of re-deriving it from Chrome pid metadata)
        out["processes"] = [p["process"] for p in payloads
                            if p.get("events")]
        return out
