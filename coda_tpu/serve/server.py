"""Threaded HTTP/JSON API over the batched session store.

The serving front door, in the dependency-free ``http.server`` style of
``demo/app.py`` (gradio/flask are not in TPU images). Worker threads do
pure host work — parse JSON, admission-control, enqueue a ticket, block on
the rendezvous — while ALL accelerator work funnels through the single
batcher thread, so N concurrent users cost one compiled slab step per tick,
not N device round trips.

    POST   /session                  {task?, seed?}    -> admit + first item
    POST   /session/{id}/label       {label, idx?}     -> update, next item
    GET    /session/{id}/best                          -> best (+ pbest)
    GET    /session/{id}/trace                         -> per-round decision
                                                          history (recorder)
    DELETE /session/{id}                               -> close, free slot
    GET    /stats                                      -> metrics snapshot
    GET    /metrics                                    -> Prometheus text
    GET    /healthz                                    -> liveness/draining

Admission control: a full slab answers 503 (the client's retry signal), as
does a draining server. ``ServeApp.drain()`` stops admitting, finishes the
queued work, and flushes metrics — the graceful-shutdown half of the
contract.

Run:  python -m coda_tpu.cli serve [--task T | --synthetic H,N,C] [--port P]
"""

from __future__ import annotations

import argparse
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from coda_tpu.serve.batcher import Batcher
from coda_tpu.serve.metrics import ServeMetrics
from coda_tpu.serve.state import (
    SelectorSpec,
    SessionStore,
    SlabFull,
    UnknownSession,
)

# how long an HTTP worker waits on its ticket before giving up (a stuck
# accelerator should surface as 504s, not piled-up threads)
REQUEST_TIMEOUT_S = 60.0


class ServeApp:
    """Store + batcher + metrics + admission policy, bundled for the
    handler (and for in-process embedding — tests and the load generator
    drive a ServeApp directly)."""

    def __init__(self, capacity: int = 64, bucket_n: int = 1,
                 max_batch: int = 256, max_wait: float = 0.002,
                 default_task: Optional[str] = None,
                 spec: Optional[SelectorSpec] = None,
                 telemetry=None, recorder=None):
        from coda_tpu.telemetry import SessionRecorder, Telemetry

        self.store = SessionStore(capacity=capacity, bucket_n=bucket_n)
        self.metrics = ServeMetrics()
        # always live (registry-backed /metrics needs one); --telemetry-dir
        # upgrades it to an artifact-writing instance
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        # per-session decision streams: always live in memory (the
        # GET /session/{id}/trace payload); --record-dir upgrades to
        # crash-safe append-only JSONL files per session
        self.recorder = recorder if recorder is not None \
            else SessionRecorder()
        self.batcher = Batcher(self.store, self.metrics,
                               max_batch=max_batch, max_wait=max_wait,
                               telemetry=self.telemetry,
                               recorder=self.recorder)
        self.spec = spec or SelectorSpec.create("coda", n_parallel=capacity)
        self.default_task = default_task
        self.draining = False
        self._seed_lock = threading.Lock()
        self._next_seed = 0
        # create the record/replay counters eagerly so /metrics exposes
        # them at 0 instead of omitting them until first use
        self.telemetry.counter(
            "serve_record_rows_total",
            "Per-round decision rows streamed by the serving recorder")
        self.telemetry.counter(
            "records_written_total",
            "Flight-recorder run records written")
        self.telemetry.counter(
            "replay_verified_total",
            "Replay verifications that matched their record")

    def add_task(self, name: str, preds, class_names=None, model_names=None,
                 default: bool = False) -> None:
        self.store.register_task(name, preds, class_names=class_names,
                                 model_names=model_names)
        if default or self.default_task is None:
            self.default_task = name

    def start(self) -> "ServeApp":
        self.batcher.start()
        return self

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new sessions, finish queued requests."""
        self.draining = True
        self.batcher.stop(drain=True, timeout=timeout)
        self.recorder.close_all()

    def _auto_seed(self) -> int:
        with self._seed_lock:
            s = self._next_seed
            self._next_seed += 1
            return s

    # -- the session verbs (shared by HTTP handler and in-process callers) -
    def open_session(self, task: Optional[str] = None,
                     seed: Optional[int] = None) -> dict:
        if self.draining:
            self.metrics.record_session("reject")
            raise Draining()
        task = task or self.default_task
        if task is None:
            raise KeyError("no task registered")
        try:
            sess = self.store.open(task, self.spec,
                                   seed=self._auto_seed() if seed is None
                                   else int(seed))
        except SlabFull:
            self.metrics.record_session("reject")
            raise
        self.metrics.record_session("open")
        self.recorder.open(sess.sid, meta={
            "task": sess.task, "method": self.spec.method,
            "seed": sess.seed})
        # first item + prior best come from the session's first dispatch;
        # if it fails (stuck accelerator -> timeout, dispatch error) the
        # client never learns the session id, so free the slot here or it
        # leaks until restart
        try:
            res = self.batcher.submit_start(sess).wait(REQUEST_TIMEOUT_S)
        except BaseException:
            self.store.close(sess.sid)
            self.recorder.close(sess.sid)
            self.metrics.record_session("close")
            raise
        return self._payload(sess, res)

    def label(self, sid: str, label: int, idx: Optional[int] = None) -> dict:
        sess = self.store.get(sid)
        cur = sess.last
        if not cur:
            raise UnknownSession(sid)  # start dispatch never completed
        if idx is not None and int(idx) != cur["next_idx"]:
            raise StaleItem(
                f"session {sid} proposed item {cur['next_idx']}, "
                f"got a label for {idx}")
        label = int(label)
        if not 0 <= label < sess.bucket.n_classes:
            raise ValueError(f"label {label} out of range "
                             f"[0, {sess.bucket.n_classes})")
        res = self.batcher.submit_label(
            sess, idx=cur["next_idx"], label=label,
            prob=cur["next_prob"]).wait(REQUEST_TIMEOUT_S)
        return self._payload(sess, res)

    def best(self, sid: str) -> dict:
        sess = self.store.get(sid)
        out = self._payload(sess, sess.last or None)
        with sess.bucket.lock:
            pbest = sess.bucket.pbest(sess.slot)
        if pbest is not None:
            out["pbest"] = pbest.tolist()
        return out

    def close_session(self, sid: str) -> dict:
        self.store.close(sid)
        self.recorder.close(sid)
        self.metrics.record_session("close")
        return {"closed": sid}

    def trace(self, sid: str) -> dict:
        """The session's per-round decision history from its record stream
        (the flight recorder's interactive face: every dispatch this
        session rode, with the proposed item, best-model answer, and the
        label that was applied)."""
        sess = self.store.get(sid)   # raises UnknownSession for dead ids
        rounds = self.recorder.history(sid) or []
        return {"session": sid, "task": sess.task,
                "n_labeled": sess.n_labeled, "rounds": rounds}

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["live_sessions"] = self.store.live_sessions()
        snap["draining"] = self.draining
        # flight-recorder evidence, in distinct units: run RECORDS written
        # process-wide (registry counter) vs per-dispatch decision ROWS
        # this server streamed — plus the replay counter (a replay running
        # in-process shows up here next to the serving numbers)
        reg = self.telemetry.registry
        snap["records_written"] = int(
            reg.counter("records_written_total").value())
        snap["record_rows_written"] = int(self.recorder.rows_written)
        snap["replay_verified"] = int(
            reg.counter("replay_verified_total").value())
        snap["buckets"] = [
            {"task": b.task, "method": b.spec.method,
             "shape": list(b.shape), "capacity": b.capacity, "live": b.live}
            for b in self.store.buckets()
        ]
        return snap

    def _payload(self, sess, res: Optional[dict]) -> dict:
        out = {
            "session": sess.sid,
            "task": sess.task,
            "n_labeled": sess.n_labeled,
        }
        if res:
            out.update({
                "idx": res["next_idx"],
                "prob": res["next_prob"],
                "best": res["best"],
                "stochastic": res["stochastic"],
            })
        return out


class Draining(RuntimeError):
    """New sessions refused: the server is shutting down."""


class StaleItem(ValueError):
    """The labeled idx is not the item the session proposed."""


_SESSION_RE = re.compile(r"^/session/([0-9a-f]+)(/(label|best|trace))?$")


class Handler(BaseHTTPRequestHandler):
    app: ServeApp = None  # set by make_server

    def log_message(self, *a):  # quiet
        pass

    def _json(self, obj, code: int = 200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, body: str, content_type: str, code: int = 200):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")

    def _route(self, method: str):
        app = self.app
        path = self.path.split("?")[0]
        m = _SESSION_RE.match(path)
        if method == "POST" and path == "/session":
            req = self._body()
            return app.open_session(task=req.get("task"),
                                    seed=req.get("seed"))
        if m and method == "POST" and m.group(3) == "label":
            req = self._body()
            if "label" not in req:
                raise ValueError("missing 'label'")
            return app.label(m.group(1), req["label"], idx=req.get("idx"))
        if m and method == "GET" and m.group(3) == "best":
            return app.best(m.group(1))
        if m and method == "GET" and m.group(3) == "trace":
            return app.trace(m.group(1))
        if m and method == "DELETE" and m.group(3) is None:
            return app.close_session(m.group(1))
        if method == "GET" and path == "/stats":
            return app.stats()
        if method == "GET" and path == "/healthz":
            return {"ok": not app.draining, "draining": app.draining}
        return None

    def _handle(self, method: str):
        if method == "GET" and self.path.split("?")[0] == "/metrics":
            # Prometheus text exposition, not JSON: registry counters
            # (recompiles, HBM watermarks) + the serve snapshot (dispatches,
            # occupancy, queue depth, latency quantiles). Same error
            # envelope as every other route: a render failure must answer
            # a JSON 500, never drop the connection.
            try:
                from coda_tpu.telemetry import render_prometheus

                body = render_prometheus(self.app.telemetry.registry,
                                         serve_metrics=self.app.metrics)
            except Exception as e:
                self._json({"error": f"internal: {e}"}, 500)
            else:
                self._text(body,
                           "text/plain; version=0.0.4; charset=utf-8")
            return
        try:
            out = self._route(method)
        except Draining:
            self._json({"error": "draining: not admitting new sessions"},
                       503)
        except SlabFull as e:
            self._json({"error": f"busy: {e}"}, 503)
        except UnknownSession as e:
            self.app.metrics.record_session("request_reject")
            self._json({"error": f"unknown session {e}"}, 404)
        except StaleItem as e:
            self.app.metrics.record_session("request_reject")
            self._json({"error": str(e)}, 409)
        except TimeoutError as e:
            self._json({"error": str(e)}, 504)
        except (ValueError, TypeError, KeyError) as e:
            self._json({"error": f"bad request: {e}"}, 400)
        except Exception as e:  # cancelled tickets, dispatch failures: the
            # client must get a JSON error, never a dropped connection
            self._json({"error": f"internal: {e}"}, 500)
        else:
            if out is None:
                self._json({"error": "not found"}, 404)
            else:
                self._json(out)

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_DELETE(self):
        self._handle("DELETE")


def make_server(app: ServeApp, port: int = 0,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Bind the HTTP server; ``port=0`` picks a free port (for tests)."""
    handler = type("BoundHandler", (Handler,), {"app": app})
    return ThreadingHTTPServer((host, port), handler)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="batched multi-session serving of interactive active "
                    "model selection")
    p.add_argument("--task", default=None)
    p.add_argument("--data-dir", default="data")
    p.add_argument("--synthetic", default=None, metavar="H,N,C",
                   help="serve a seeded synthetic task of this shape")
    p.add_argument("--method", default="coda",
                   help="selector behind every session "
                        "{coda, iid, uncertainty, model_picker, ...}")
    p.add_argument("--capacity", type=int, default=64,
                   help="slab slots per bucket = max concurrent sessions "
                        "per (task, config); admission past it answers 503")
    p.add_argument("--bucket-n", type=int, default=1,
                   help="pad task N up to this quantum so near-shaped tasks "
                        "share one compiled program (1 = exact shapes)")
    p.add_argument("--max-batch", type=int, default=256,
                   help="max requests coalesced into one dispatch")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="linger after the first queued request before "
                        "dispatching (the latency/occupancy dial)")
    p.add_argument("--port", type=int, default=7861)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (cpu/tpu) — same as main.py")
    p.add_argument("--tracking-db", default=None,
                   help="flush serving metrics into this MLflow-schema "
                        "sqlite DB on shutdown")
    p.add_argument("--telemetry-dir", default=None,
                   help="write trace.json (Perfetto spans: batcher ticks) "
                        "+ telemetry.json (recompiles, HBM watermarks) + "
                        "metrics.prom there on shutdown; /metrics serves "
                        "the same registry live either way")
    p.add_argument("--record-dir", default=None,
                   help="stream each session's per-round decision history "
                        "to an append-only session_<id>.jsonl there "
                        "(crash-safe: every completed dispatch is flushed); "
                        "GET /session/{id}/trace serves the same stream "
                        "live either way")
    return p.parse_args(argv)


def build_app(args) -> ServeApp:
    """ServeApp from parsed args (shared with the load generator)."""
    from coda_tpu.cli import load_dataset

    spec_kwargs = {}
    if args.method.startswith("coda"):
        # every slot carries its own incremental cache; the auto eig_mode
        # budget must see the whole slab (cli.py sets the same hint from
        # the seed-vmap width)
        spec_kwargs["n_parallel"] = args.capacity
    telemetry = None
    if getattr(args, "telemetry_dir", None):
        from coda_tpu.telemetry import Telemetry

        telemetry = Telemetry(out_dir=args.telemetry_dir)
    recorder = None
    if getattr(args, "record_dir", None):
        from coda_tpu.telemetry import SessionRecorder

        recorder = SessionRecorder(out_dir=args.record_dir)
    app = ServeApp(
        capacity=args.capacity, bucket_n=args.bucket_n,
        max_batch=args.max_batch, max_wait=args.max_wait_ms / 1e3,
        spec=SelectorSpec.create(args.method, **spec_kwargs),
        telemetry=telemetry, recorder=recorder,
    )
    if args.task or args.synthetic:
        ds = load_dataset(args)
        app.add_task(ds.name, ds.preds, class_names=ds.class_names)
    else:
        from coda_tpu.data import make_synthetic_task

        task = make_synthetic_task(seed=0, H=8, N=512, C=10)
        app.add_task(task.name, task.preds)
    return app


def main(argv=None):
    args = parse_args(argv)
    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    app = build_app(args).start()
    srv = make_server(app, args.port)
    print(f"serving {app.default_task!r} ({app.spec.method}) on "
          f"http://127.0.0.1:{srv.server_address[1]}/ — capacity "
          f"{app.store.capacity} sessions/bucket")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        print("\ndraining...")
    finally:
        app.drain()
        srv.server_close()
        if args.telemetry_dir:
            paths = app.telemetry.write(
                extra={"serve": app.metrics.snapshot()})
            print(f"telemetry written to {paths.get('telemetry')}")
        if args.tracking_db:
            from coda_tpu.tracking import TrackingStore

            store = TrackingStore(args.tracking_db)
            app.metrics.log_to_store(store, params={
                "method": app.spec.method,
                "capacity": app.store.capacity})
            store.close()
            print(f"metrics logged to {args.tracking_db}")


if __name__ == "__main__":
    main()
