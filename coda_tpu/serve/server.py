"""Asyncio HTTP/JSON front door over the batched session store.

The serving front door, dependency-free (stdlib ``asyncio`` — gradio/flask
are not in TPU images). One event loop multiplexes every connection, so
256+ concurrent sessions cost file descriptors, not OS threads: the
thread-per-request stdlib server this replaced paid thread-scheduling
jitter per click at high session counts. Handlers do pure host work —
parse JSON, admission-control, enqueue a ticket, ``await`` the rendezvous —
while ALL accelerator work funnels through the single batcher thread
(tickets bridge back into the loop via ``call_soon_threadsafe``), so N
concurrent users cost one compiled slab step per tick, not N device round
trips. Blocking host sections (admission's bucket lock, posterior reads)
run on the default executor so the loop never stalls behind them.

    POST   /session                  {task?, seed?}    -> admit + first item
    POST   /session/{id}/label       {label, idx?,
                                      request_id?}     -> update, next item
                                                          (idempotent on
                                                          request_id)
    GET    /session/{id}/best                          -> best (+ pbest)
    GET    /session/{id}/trace                         -> per-round decision
                                                          history (recorder)
    POST   /session/{id}/export      {close?}          -> migration payload
    POST   /session/import           <export payload>  -> restore, same id
    DELETE /session/{id}                               -> close, free slot
    GET    /stats                                      -> metrics snapshot
    GET    /metrics                                    -> Prometheus text
    GET    /healthz                                    -> readiness/liveness
                                                          (ok|degraded|
                                                          unready)

Admission control: with tiering (the default, ``serve/tiering.py``) a
full slab demotes its coldest idle session to the warm tier and admits —
open sessions are bounded by host RAM + spill disk, not slab capacity —
and a label/best/trace for a demoted session transparently wakes it.
503 remains the backpressure signal when nothing is demotable (every
slot pinned by an in-flight request), with ``--no-tiering``, and on a
draining server. ``ServeApp.drain()`` stops admitting, finishes the
queued work, and flushes metrics — the graceful-shutdown half of the
contract.

Warm pool: ``ServeApp.start()`` ahead-of-time compiles every (task, spec)
bucket's slab-step/init/pbest executables (``jit(...).lower().compile()``)
so first-hit compilation never lands under a user's click, and ``/healthz``
answers 503 until the pool is warm — the readiness gate a load balancer
keys on. With ``--compilation-cache-dir`` the executables persist across
restarts: a second start deserializes instead of recompiling (0 fresh
backend compiles, pinned by the warm-restart test).

Run:  python -m coda_tpu.cli serve [--task T | --synthetic H,N,C] [--port P]
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import os
import re
import socket
import threading
import time
from typing import Optional

from coda_tpu.serve.batcher import Batcher
from coda_tpu.serve.metrics import ServeMetrics
from coda_tpu.serve.recovery import ImportRejected
from coda_tpu.serve.state import (
    BucketQuarantined,
    SelectorSpec,
    SessionStore,
    SlabFull,
    StaleOwner,
    UnknownSession,
)

# how long a front-door handler waits on its ticket before giving up (a
# stuck accelerator should surface as 504s, not piled-up waiters)
REQUEST_TIMEOUT_S = 60.0


class ServeApp:
    """Store + batcher + metrics + admission policy + warm pool, bundled
    for the front door (and for in-process embedding — tests and the load
    generator drive a ServeApp directly)."""

    def __init__(self, capacity: int = 64, bucket_n: int = 1,
                 max_batch: int = 256, max_wait: float = 0.002,
                 max_linger: Optional[float] = None,
                 default_task: Optional[str] = None,
                 spec: Optional[SelectorSpec] = None,
                 step_impl: Optional[str] = None, donate: bool = True,
                 telemetry=None, recorder=None,
                 fault_spec: Optional[str] = None,
                 tiering: bool = True,
                 tier_spill_dir: Optional[str] = None,
                 idle_warm_s: float = 30.0, idle_cold_s: float = 120.0,
                 max_warm: int = 8192, tier_free_fraction: float = 0.0,
                 tracing: bool = True, quality: bool = True,
                 quality_audit_frac: float = 0.25):
        from coda_tpu.serve.faults import FaultInjector
        from coda_tpu.serve.recovery import BucketHealer
        from coda_tpu.serve.tiering import TierManager
        from coda_tpu.telemetry import SessionRecorder, Telemetry

        # deterministic fault injection (--fault-spec); inert when unset —
        # every site checks `faults is not None` first
        self.faults = FaultInjector(fault_spec) if fault_spec else None
        # distributed tracing (telemetry/trace.py): when on, session verbs
        # accept a trace context, record a serve span under it, and hand
        # it to their ticket (tick/step span links, recorder rows, metric
        # exemplars). NEVER read by dispatch math — `--no-trace` and
        # tracing-on produce bitwise-identical session trajectories.
        self.tracing = bool(tracing)
        self.store = SessionStore(capacity=capacity, bucket_n=bucket_n,
                                  step_impl=step_impl, donate=donate,
                                  faults=self.faults)
        self.metrics = ServeMetrics()
        # always live (registry-backed /metrics needs one); --telemetry-dir
        # upgrades it to an artifact-writing instance
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        # per-session decision streams: always live in memory (the
        # GET /session/{id}/trace payload); --record-dir upgrades to
        # crash-safe append-only JSONL files per session
        self.recorder = recorder if recorder is not None \
            else SessionRecorder(faults=self.faults)
        # warm-pool cost gauges must land in THIS app's registry (the one
        # /metrics renders), not the process-global default — a custom-
        # registry Telemetry would otherwise never show its buckets' costs
        self.store.registry = self.telemetry.registry
        if self.faults is not None and \
                getattr(self.recorder, "faults", None) is None:
            # an injected recorder joins the fault domain too (record_eio)
            self.recorder.faults = self.faults
        # decision-quality plane (telemetry/quality.py): live calibration
        # of the served posterior, drift detectors, and the shadow auditor
        # that bitwise-replays a sample of closed sessions off the batcher
        # thread. NEVER read by dispatch math — `--no-quality` and
        # quality-on produce bitwise-identical decision rows (the only
        # stream delta is the additive-optional `pred_label_prob` field).
        self.quality = None
        if quality:
            from coda_tpu.telemetry.quality import QualityPlane

            self.quality = QualityPlane(
                preds_fn=self.store.task_preds, faults=self.faults,
                registry=self.telemetry.registry,
                audit_frac=quality_audit_frac)
            self.metrics.quality_provider = self.quality.snapshot
        self.batcher = Batcher(self.store, self.metrics,
                               max_batch=max_batch, max_wait=max_wait,
                               max_linger=max_linger,
                               telemetry=self.telemetry,
                               recorder=self.recorder,
                               faults=self.faults,
                               quality=self.quality)
        # surrogate-scorer evidence (--eig-scorer surrogate:k buckets):
        # /stats and /metrics read the slab-carried fit counters on
        # demand through the snapshot provider — never a per-tick sync
        self.metrics.surrogate_provider = self._surrogate_totals
        # bucket self-healing: a dispatch that quarantines a bucket (step
        # failure consumed the donated carries) schedules a slab rebuild
        # from the sessions' recorder streams, digest-verified
        self.healer = BucketHealer(self.store, self.recorder,
                                   metrics=self.metrics)
        self.batcher.on_bucket_failure = self.healer.schedule
        # tiered posterior state (serve/tiering.py): hot sessions on the
        # slab, warm sessions as host-RAM export payloads, cold sessions
        # hibernated to tier_spill_dir; admission past capacity demotes
        # the coldest instead of 503, a label/best/trace for a
        # non-resident session transparently wakes it
        self.tiers = TierManager(
            self, spill_dir=tier_spill_dir, idle_warm_s=idle_warm_s,
            idle_cold_s=idle_cold_s, max_warm=max_warm,
            free_fraction=tier_free_fraction) if tiering else None
        self.spec = spec or SelectorSpec.create("coda", n_parallel=capacity)
        # cross-session surrogate prior pool (serve/priors.py): live only
        # when the spec's surrogate_prior knob says 'pool' — under the
        # default 'off' there is no pool, no provider, and admission
        # seeds nothing (the PR-14 bitwise pin)
        self.prior_pool = None
        _prior_knob = dict(getattr(self.spec, "kwargs", ()) or ()).get(
            "surrogate_prior", "off")
        from coda_tpu.selectors.surrogate import parse_prior

        if parse_prior(str(_prior_knob)):
            from coda_tpu.serve.priors import PriorPool

            self.prior_pool = PriorPool()
            self.metrics.prior_provider = self._prior_totals
            # lazily-built buckets resolve their seed prior at build time
            from coda_tpu.serve.priors import bucket_pool_key

            self.store.prior_resolver = (
                lambda b: self.prior_pool.get(bucket_pool_key(self, b)))
        self.default_task = default_task
        self.draining = False
        # migration holds (the fleet's prepare/commit protocol): a held
        # sid is mid-migration — its export payload is in the router's
        # hands and neither a local label commit nor a wake may revive
        # the local copy until the router fences (drop) or aborts
        # (resume). Guarded by store.lock.
        self._holds: set = set()
        self.warm_error: Optional[str] = None  # last warm-up failure
        # readiness: set once the warm pool is compiled (or warm-up was
        # explicitly skipped). /healthz answers 503 until then — the load
        # balancer's signal to keep traffic off a still-compiling replica.
        self.ready = threading.Event()
        self.warm_info: dict = {}
        self._warm_requested = False  # whether start() asked for the pool
        self._seed_lock = threading.Lock()
        self._next_seed = 0
        # blocking-verb executor for the asyncio front door: sized for a
        # thundering herd of admissions (each blocks ~one init executable,
        # not a slab step — admission writes are staged, see state.py), so
        # the default 5-thread loop executor never becomes the bottleneck
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="serve-verb")
        # create the record/replay counters eagerly so /metrics exposes
        # them at 0 instead of omitting them until first use
        self.telemetry.counter(
            "serve_record_rows_total",
            "Per-round decision rows streamed by the serving recorder")
        self.telemetry.counter(
            "records_written_total",
            "Flight-recorder run records written")
        self.telemetry.counter(
            "replay_verified_total",
            "Replay verifications that matched their record")

    def add_task(self, name: str, preds, class_names=None, model_names=None,
                 default: bool = False) -> None:
        self.store.register_task(name, preds, class_names=class_names,
                                 model_names=model_names)
        if default or self.default_task is None:
            self.default_task = name

    # -- warm pool ---------------------------------------------------------
    def warm(self) -> dict:
        """AOT-compile every (task, spec) bucket's executables.

        Enumerates the warm pool — each registered task under this server's
        selector spec — builds the bucket (selector statics) and compiles
        its slab step, per-slot init, and pbest read ahead of time. Backed
        by a persistent compilation cache (``--compilation-cache-dir``)
        this is a deserialization pass on restart, not a compile pass.
        Sets readiness when done; returns {size, warm_s, buckets}."""
        t0 = time.perf_counter()
        n_exec = 0
        tasks = self.store.tasks()
        for task in tasks:
            bucket = self.store._bucket_for(task, self.spec)
            n_exec += bucket.warm()["executables"]
        wall = time.perf_counter() - t0
        self.metrics.record_warm_pool(n_exec, wall)
        self.warm_info = {"size": n_exec, "warm_s": wall,
                          "buckets": len(tasks)}
        self.ready.set()
        return dict(self.warm_info)

    def _warm_background(self) -> None:
        try:
            info = self.warm()
            print(f"warm pool ready: {info['size']} executables in "
                  f"{info['warm_s']:.1f}s")
        except Exception as e:  # degraded but serviceable: the lazy-jit
            # fallback still answers; readiness unblocks so the server
            # isn't bricked by one bucket's warm-up failure. Routed
            # through the telemetry registry (not a bare print) so the
            # failure is visible on /metrics, /stats, and /healthz
            # (status "degraded"), not just a scrolled-away console line.
            self._record_warm_failure(e)
            self.ready.set()

    def _record_warm_failure(self, e: BaseException) -> None:
        self.warm_error = repr(e)
        reg = self.telemetry.registry
        reg.counter("serve_warmup_failures_total",
                    "Warm-pool compilations that failed (server degraded "
                    "to lazy jit)").inc()
        reg.gauge("serve_warmup_last_failure_timestamp",
                  "Unix time of the last warm-pool failure").set(
                      # wall-clock: a *_timestamp gauge carries Unix time
                      time.time())
        print(f"warm-up failed ({e}); serving with lazy compilation")

    def start(self, warm: bool = True,
              warm_async: bool = False) -> "ServeApp":
        self._warm_requested = warm
        self.batcher.start()
        if self.tiers is not None:
            self.tiers.start()
        if not warm:
            self.ready.set()
        elif warm_async:
            threading.Thread(target=self._warm_background, daemon=True,
                             name="serve-warmup").start()
        else:
            # same degrade-don't-crash contract as the background path: a
            # warm-up failure leaves a serviceable lazy-jit server (the
            # --restore startup warms synchronously and must not be
            # bricked by one bucket's compile failure)
            self._warm_background()
        return self

    def quiesce(self, timeout: float = 30.0, hard: bool = False) -> None:
        """Stop admitting and stop ticking — but keep sessions, recorder
        streams, and the executor alive. The migration half-step: after
        quiesce, every live session can be exported
        (``recovery.export_all``) and handed to a fresh server; ``drain``
        completes the shutdown.

        Default: finish queued work first. ``hard`` cuts immediately —
        queued tickets fail with a retryable error and land on the new
        server via client retry; under LIVE retrying load this is the
        only cut that leaves sessions to migrate (a soft drain races the
        clients, who keep finishing and closing sessions while the queue
        waits to go quiet)."""
        self.draining = True
        if self.tiers is not None:
            self.tiers.stop()  # no demotions mid-migration sweep
        self.batcher.stop(drain=not hard, timeout=timeout)

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new sessions, finish queued requests."""
        self.quiesce(timeout=timeout)
        self.recorder.close_all()
        if self.quality is not None:
            self.quality.stop()
        self._executor.shutdown(wait=False)

    def _auto_seed(self) -> int:
        with self._seed_lock:
            s = self._next_seed
            self._next_seed += 1
            return s

    # -- distributed tracing glue ------------------------------------------
    def _trace_child(self, trace_ctx):
        """Continue the caller's trace on this replica: a fresh span under
        the same trace, parented to the caller's span. None when untraced
        or tracing is off — every downstream consumer checks for None."""
        if trace_ctx is None or not self.tracing:
            return None
        return trace_ctx.child()

    @contextlib.contextmanager
    def _serve_span(self, verb: str, ctx):
        """Record ``serve/<verb>`` on the ``host:serve`` lane under ``ctx``.

        Records on EVERY exit — a fenced/held attempt (StaleOwner, hold
        window) still leaves this replica's lane in the trace, which is
        exactly how a request retried across a migration shows both
        replicas' lanes in one stitched file. No-op when untraced."""
        if ctx is None:
            yield
            return
        t0 = time.perf_counter()
        attrs = ctx.attrs()
        try:
            yield
        except BaseException as e:
            attrs["error"] = type(e).__name__
            raise
        finally:
            self.telemetry.spans.record(f"serve/{verb}", lane="host:serve",
                                        t_start=t0,
                                        t_end=time.perf_counter(),
                                        attrs=attrs)

    def trace_by_id(self, trace_id: str) -> dict:
        """This replica's retained spans for one trace — the
        ``GET /trace/id/{trace_id}`` payload the router's collector
        stitches (empty events when unknown/evicted, never a 404: an
        evicted trace is a fact, not an error)."""
        return self.telemetry.spans.trace_payload(str(trace_id))

    # -- fencing + migration holds -----------------------------------------
    def held(self, sid: str) -> bool:
        with self.store.lock:
            return sid in self._holds

    def _check_hold(self, sid: str) -> None:
        if self.held(sid):
            # retryable: the move either commits (the retry re-routes to
            # the new owner) or aborts (the retry lands back here)
            raise BucketQuarantined(
                f"session {sid} is migrating; retry shortly")

    def _check_epoch(self, sess, epoch) -> None:
        """The fencing check: a verb stamped with an ownership epoch
        NEWER than this copy's proves the session migrated away and this
        copy is stale — refuse, typed, so the router re-locates. A verb
        stamped older/equal is fine (a restarted router's map can lag; a
        newer local copy is still the authority)."""
        if epoch is not None and int(epoch) > sess.epoch:
            self.metrics.record_fencing_rejection()
            raise StaleOwner(sess.sid, have=sess.epoch, want=int(epoch))

    def session_epoch(self, sid: str) -> dict:
        """The ownership epoch of this replica's copy, without waking it
        (``GET /session/{id}/epoch`` — the journal-recovery probe; a
        full export just to read one integer would ship the whole
        stream)."""
        try:
            return {"session": sid, "epoch": self.store.get(sid).epoch}
        except UnknownSession:
            if self.tiers is not None:
                p = self.tiers.parked_payload(sid)
                if p is not None:
                    return {"session": sid,
                            "epoch": int(p.get("epoch") or 0)}
            raise

    def begin_migration(self, sid: str) -> dict:
        """The migration PREPARE verb: quiesce (demote until parked — the
        demotion loses cleanly to any in-flight label ticket, so the
        payload always carries every committed label), place a hold (no
        local commit, no wake can revive the copy), and export WITHOUT
        closing — the source keeps a recoverable copy until the router's
        fence commits the move. A lost response is therefore harmless:
        nothing changed hands yet."""
        if self.tiers is not None:
            for _ in range(500):
                if not self.store.alive(sid):
                    break  # already parked (or closed) — export serves it
                if self.tiers.try_demote(sid):
                    break
                time.sleep(0.002)
        else:
            # no tiering: the session stays hot — wait out in-flight
            # tickets (pins) so the export snapshot trails every commit
            try:
                sess = self.store.get(sid)
                for _ in range(500):
                    if sess.pins == 0:
                        break
                    time.sleep(0.002)
            except UnknownSession:
                pass
        with self.store.lock:
            self._holds.add(sid)
        try:
            return self.export_session(sid, close=False)
        except BaseException:
            with self.store.lock:
                self._holds.discard(sid)
            raise

    def end_migration(self, sid: str, drop: bool) -> dict:
        """The migration COMMIT/ABORT verb. ``drop=True`` fences the
        local copy (the peer owns the session now): discard the parked
        payload / close the live copy and seal its stream. ``drop=False``
        lifts the hold — the move failed and the session resumes here,
        untouched."""
        with self.store.lock:
            held = sid in self._holds
            self._holds.discard(sid)
        if not drop:
            return {"session": sid, "held": held, "dropped": False}
        dropped = False
        try:
            if self.store.alive(sid):
                self.store.close(sid)
                self.recorder.close(sid)
                dropped = True
        except UnknownSession:
            pass
        if not dropped and self.tiers is not None and \
                self.tiers.discard(sid):
            self.recorder.seal(sid)
            dropped = True
        if dropped:
            self.metrics.record_session("close")
        return {"session": sid, "held": held, "dropped": dropped}

    # -- tiering glue: wake-through lookup + demote-then-admit -------------
    def _resolve_pinned(self, sid: str, wake: bool = True):
        """Session lookup that pages a non-resident session back in: a
        label/best/trace for a warm or cold sid wakes it through the
        import fast path instead of 404-ing. Returns the session PINNED
        (undemotable) — every caller unpins on every exit path.
        ``wake=False`` skips paging (the event-loop fast path, which
        must never run a wake's disk/replay work inline)."""
        misses = 0
        for _ in range(8):
            try:
                return self.store.get_pinned(sid)
            except UnknownSession:
                if self.tiers is None or not wake:
                    raise
                if self.tiers.wake_if_parked(sid):
                    continue
                # in no tier map — either truly unknown, or inside a
                # demotion's unpublish→publish window (store pop, slot
                # release, stream park all precede the warm-map insert):
                # wait that window out before answering 404
                misses += 1
                if misses > 3:
                    raise
                time.sleep(0.002)
        return self.store.get_pinned(sid)

    def _admit(self, task: str, seed: int, sid: Optional[str] = None,
               restoring: bool = False):
        """``store.open`` with tiering admission: past slab capacity the
        coldest resident session is demoted and the open retried —
        ``SlabFull`` (503) only when nothing is demotable (every slot
        pinned by an in-flight verb), which is genuine backpressure."""
        attempts = 16 if self.tiers is not None else 1
        for i in range(attempts):
            try:
                return self.store.open(task, self.spec, seed=seed, sid=sid,
                                       restoring=restoring)
            except SlabFull:
                if self.tiers is None or i == attempts - 1:
                    raise
                if not self.tiers.make_room_for(task, self.spec):
                    # transient: every candidate is pinned by a concurrent
                    # verb or another demoter mid-sweep — wait a beat for
                    # the herd to clear instead of bouncing a 503 the
                    # client would only retry anyway
                    time.sleep(0.002)

    # -- the session verbs (shared by the front door and in-process
    #    callers; *_begin/_abort split out so the asyncio path can run the
    #    blocking host half on an executor and await only the ticket) ------
    def _open_begin(self, task: Optional[str], seed: Optional[int],
                    sid: Optional[str] = None, trace=None):
        from coda_tpu.serve.batcher import Ticket
        from coda_tpu.serve.recovery import _SID_RE

        if self.draining:
            self.metrics.record_session("reject")
            raise Draining()
        task = task or self.default_task
        if task is None:
            raise KeyError("no task registered")
        if sid is not None:
            # a fleet router pins the session id at open so placement is
            # rendezvous-on-id; it must still be the hex form the HTTP
            # routes (and the recorder file layout) can address
            if not _SID_RE.match(str(sid)):
                raise ValueError(f"invalid session id {sid!r}: expected "
                                 "lowercase hex")
            if self.tiers is not None and self.tiers.parked(sid):
                # the store only collides against LIVE sids; a parked
                # session is still addressable, and re-opening its id
                # would put two states under one identity (the stale
                # parked copy would wake later under the new client's
                # handle)
                raise ValueError(f"session id {sid!r} already exists "
                                 "(parked in the warm/cold tier)")
        try:
            sess = self._admit(task, self._auto_seed() if seed is None
                               else int(seed), sid=sid)
        except SlabFull:
            self.metrics.record_session("reject")
            raise
        self.metrics.record_session("open")
        tm = self.store.task_meta(sess.task)
        # everything crash restore / offline replay needs to rebuild this
        # session from its stream alone: selector config, and the dataset
        # shape+digest guard (replaying against different data answers a
        # different question)
        self.recorder.open(sess.sid, meta={
            "task": sess.task, "method": self.spec.method,
            "spec_kwargs": [list(kv) for kv in self.spec.kwargs],
            "acq_batch": self.spec.acq_batch,
            "seed": sess.seed, "shape": tm.get("shape"),
            "digest": tm.get("digest"),
            # the applied-prior record (pool values + digest + credit)
            # ONLY when this admission was actually seeded — cold
            # sessions keep the exact pre-prior meta, so their streams
            # stay bitwise identical to PR-14 ones
            **({"surrogate_prior": dict(sess.prior_fit)}
               if sess.prior_fit is not None else {})})
        # the start ticket carries a demotion pin (set BEFORE submit so a
        # racing sweep can never page out a session whose first dispatch
        # is still in flight); resolution — result, error, or timeout
        # cancel — releases it exactly once
        self.store.pin(sess)
        ticket = Ticket(session=sess, do_update=False, trace=trace)
        ticket.on_resolve = lambda: self.store.unpin(sess)
        return sess, self.batcher.submit(ticket)

    def _open_abort(self, sess) -> None:
        # first item + prior best come from the session's first dispatch;
        # if it fails (stuck accelerator -> timeout, dispatch error) the
        # client never learns the session id, so free the slot here or it
        # leaks until restart
        self.store.close(sess.sid)
        self.recorder.close(sess.sid)
        self.metrics.record_session("close")

    def open_session(self, task: Optional[str] = None,
                     seed: Optional[int] = None,
                     sid: Optional[str] = None, trace_ctx=None) -> dict:
        my = self._trace_child(trace_ctx)
        with self._serve_span("open", my):
            sess, ticket = self._open_begin(task, seed, sid=sid, trace=my)
            try:
                res = ticket.wait(REQUEST_TIMEOUT_S)
            except BaseException:
                self._open_abort(sess)
                raise
            return self._payload(sess, res)

    async def open_session_async(self, task: Optional[str] = None,
                                 seed: Optional[int] = None,
                                 sid: Optional[str] = None,
                                 trace_ctx=None) -> dict:
        loop = asyncio.get_running_loop()
        my = self._trace_child(trace_ctx)
        with self._serve_span("open", my):
            if (self.recorder.out_dir is None
                    and self.store.has_fast_admission(
                        task or self.default_task or "", self.spec)):
                # warm-pool fast path: admission is sub-ms host work
                # (free-slot pop + staged cached-init write), so run it
                # inline — a thundering herd of opens then queues in one
                # burst instead of trickling through executor threads and
                # stretching the first tick's formation window to its cap.
                # A file-backed recorder disqualifies the fast path:
                # recorder.open() would do disk I/O (and contend on the
                # recorder lock with the batcher's per-row flushes) on
                # the event loop.
                sess, ticket = self._open_begin(task, seed, sid=sid,
                                                trace=my)
            else:
                # unseen (task, spec) or cold bucket: bucket construction /
                # per-admission init compute runs for real — never on the
                # event loop
                sess, ticket = await loop.run_in_executor(
                    self._executor,
                    lambda: self._open_begin(task, seed, sid=sid, trace=my))
            try:
                res = await ticket.wait_async(REQUEST_TIMEOUT_S)
            except BaseException:
                await loop.run_in_executor(self._executor, self._open_abort,
                                           sess)
                raise
            return self._payload(sess, res)

    def _label_begin(self, sid: str, label: int, idx: Optional[int],
                     request_id: Optional[str] = None, wake: bool = True,
                     epoch: Optional[int] = None, trace=None):
        from coda_tpu.serve.batcher import Ticket

        if self.faults is not None and self.tiers is not None and \
                "demote_during_label" in self.faults.fire("label_pre"):
            # injected demotion at the exact moment a label arrives: it
            # either wins (and the lookup below transparently wakes the
            # session) or loses cleanly to an in-flight pin — never both
            self.tiers.try_demote(sid)
        # a held sid is mid-migration: refuse retryably BEFORE the wake-
        # through lookup (a wake would revive the copy the export of
        # which is already in the router's hands)
        self._check_hold(sid)
        # wake-through lookup, PINNED: the session cannot be demoted
        # between here and the ticket's resolution (the pin is handed to
        # the ticket below; every non-ticket exit unpins in `finally`)
        sess = self._resolve_pinned(sid, wake=wake)
        handoff = False
        try:
            # the fence: a stale copy must refuse BEFORE the dedupe
            # lookup — its cache predates the migration, and answering
            # from it would commit a label the new owner also commits
            self._check_epoch(sess, epoch)
            if sess.restoring:
                # import/restore is mid-replay: the posterior and the
                # dedupe cache are not rebuilt yet, so a label now could
                # double-apply — retryable 503, same contract as the
                # quarantine heal
                raise BucketQuarantined(
                    f"session {sid} is being restored; retry shortly")
            # idempotent retries: a request_id the session has already
            # applied (or has in flight) is answered from the committed
            # result / the live ticket — the oracle answer is applied to
            # the posterior EXACTLY once no matter how many times the
            # client retries. Checked BEFORE the stale-idx guard: a retry
            # of an applied label is stale by definition, and that
            # staleness is precisely what it means to have already been
            # applied. Restore/import repopulate the cache from the
            # recorder stream, so dedupe survives migration too.
            if request_id is not None:
                with self.store.lock:
                    done = sess.recent.get(request_id)
                    inflight = None if done is not None else \
                        sess.pending.get(request_id)
                    if inflight is not None and inflight.done.is_set() \
                            and inflight.error is not None:
                        inflight = None  # dead ticket: retry resubmits
                if done is not None:
                    t = Ticket(session=sess, do_update=True,
                               request_id=request_id)
                    t.complete(dict(done))
                    return sess, t
                if inflight is not None:
                    return sess, inflight
            cur = sess.last
            if not cur:
                raise UnknownSession(sid)  # start dispatch never completed
            # batch-label sessions (acq_batch q > 1): the session proposes
            # q items per round and ``label`` arrives as a length-q list —
            # all q oracle answers resolve through this ONE ticket/dispatch
            q = sess.bucket.acq_batch
            if q > 1:
                if not isinstance(label, (list, tuple)):
                    raise ValueError(
                        f"session {sid} batches {q} labels per round; "
                        "POST /session/{id}/labels with a 'labels' list")
                if len(label) != q:
                    raise ValueError(
                        f"session {sid} expects exactly {q} labels per "
                        f"round, got {len(label)}")
                if idx is not None:
                    if (not isinstance(idx, (list, tuple))
                            or [int(i) for i in idx]
                            != [int(i) for i in cur["next_idx"]]):
                        raise StaleItem(
                            f"session {sid} proposed items "
                            f"{cur['next_idx']}, got labels for {idx}")
                label = [int(v) for v in label]
                for v in label:
                    if not 0 <= v < sess.bucket.n_classes:
                        raise ValueError(
                            f"label {v} out of range "
                            f"[0, {sess.bucket.n_classes})")
            else:
                if isinstance(label, (list, tuple)):
                    if len(label) != 1:
                        raise ValueError(
                            f"session {sid} labels one item per round, "
                            f"got {len(label)} labels")
                    label = label[0]
                    if isinstance(idx, (list, tuple)):
                        idx = idx[0] if idx else None
                if idx is not None and int(idx) != cur["next_idx"]:
                    raise StaleItem(
                        f"session {sid} proposed item {cur['next_idx']}, "
                        f"got a label for {idx}")
                label = int(label)
                if not 0 <= label < sess.bucket.n_classes:
                    raise ValueError(f"label {label} out of range "
                                     f"[0, {sess.bucket.n_classes})")
            ticket = Ticket(session=sess, do_update=True,
                            idx=cur["next_idx"],
                            label=label, prob=cur["next_prob"],
                            request_id=request_id, trace=trace)
            if request_id is not None:
                # registration is atomic with a re-check, so two
                # concurrent retries of the same request_id can never
                # BOTH submit
                with self.store.lock:
                    done = sess.recent.get(request_id)
                    if done is None:
                        existing = sess.pending.get(request_id)
                        if existing is not None and not (
                                existing.done.is_set()
                                and existing.error is not None):
                            return sess, existing
                        sess.pending[request_id] = ticket
                if done is not None:
                    ticket.complete(dict(done))
                    return sess, ticket
            # the ticket inherits our pin; resolution releases it
            ticket.on_resolve = lambda: self.store.unpin(sess)
            handoff = True
            return sess, self.batcher.submit(ticket)
        finally:
            if not handoff:
                self.store.unpin(sess)

    def label(self, sid: str, label: int, idx: Optional[int] = None,
              request_id: Optional[str] = None,
              epoch: Optional[int] = None, trace_ctx=None) -> dict:
        my = self._trace_child(trace_ctx)
        with self._serve_span("label", my):
            sess, ticket = self._label_begin(sid, label, idx, request_id,
                                             epoch=epoch, trace=my)
            return self._payload(sess, ticket.wait(REQUEST_TIMEOUT_S))

    async def label_async(self, sid: str, label: int,
                          idx: Optional[int] = None,
                          request_id: Optional[str] = None,
                          epoch: Optional[int] = None,
                          trace_ctx=None) -> dict:
        my = self._trace_child(trace_ctx)
        with self._serve_span("label", my):
            try:
                # inline fast path with waking DISABLED: for a resident
                # session _label_begin is pure host-dict work (lookup,
                # bounds checks, queue.put) — microseconds on the loop.
                # wake=False (not a pre-check) closes the race where a
                # demotion lands between an aliveness probe and the
                # lookup: the wake's disk read / stream replay must never
                # run on the event loop.
                sess, ticket = self._label_begin(sid, label, idx,
                                                 request_id, wake=False,
                                                 epoch=epoch, trace=my)
            except UnknownSession:
                if self.tiers is None:
                    raise
                # non-resident (or mid-demotion): the full wake-through
                # path on the executor — it retries through the demotion
                # window and re-raises UnknownSession only for truly
                # dead sids
                loop = asyncio.get_running_loop()
                sess, ticket = await loop.run_in_executor(
                    self._executor,
                    lambda: self._label_begin(sid, label, idx, request_id,
                                              epoch=epoch, trace=my))
            return self._payload(
                sess, await ticket.wait_async(REQUEST_TIMEOUT_S))

    def labels(self, sid: str, labels, idx=None,
               request_id: Optional[str] = None,
               epoch: Optional[int] = None, trace_ctx=None) -> dict:
        """The batch-label verb behind ``POST /session/{id}/labels``: all
        q oracle answers of one round, resolved through ONE ticket and
        ONE fused dispatch (the q-wide bucket's compiled step applies
        them as a single multi-row posterior update and proposes the next
        q items). Idempotent per ``request_id`` exactly like ``label`` —
        the batch commits to the posterior at most once no matter how
        many times the client retries. On an acq_batch=1 session a
        single-element list degrades to the plain label path.

        ``_label_begin`` is list-aware, so both verbs ARE the label
        verbs with a list payload — no second copy of the pin/dedupe/
        wake choreography to keep in lockstep."""
        return self.label(sid, list(labels), idx=idx,
                          request_id=request_id, epoch=epoch,
                          trace_ctx=trace_ctx)

    async def labels_async(self, sid: str, labels, idx=None,
                           request_id: Optional[str] = None,
                           epoch: Optional[int] = None,
                           trace_ctx=None) -> dict:
        return await self.label_async(sid, list(labels), idx=idx,
                                      request_id=request_id, epoch=epoch,
                                      trace_ctx=trace_ctx)

    def answer(self, sid: str, slot, label=None,
               request_id: Optional[str] = None,
               epoch: Optional[int] = None, abstain: bool = False,
               trace_ctx=None) -> dict:
        my = self._trace_child(trace_ctx)
        with self._serve_span("answer", my):
            return self._answer_impl(sid, slot, label=label,
                                     request_id=request_id, epoch=epoch,
                                     abstain=abstain, trace=my)

    def _answer_impl(self, sid: str, slot, label=None,
                     request_id: Optional[str] = None,
                     epoch: Optional[int] = None, abstain: bool = False,
                     trace=None) -> dict:
        """The asynchronous oracle verb (``POST /session/{id}/answer``):
        ONE per-slot crowd answer of the current round, in ANY order.

        Where ``labels`` demands all q answers at once, a crowd delivers
        them one by one — noisy, late, out of order, some abstaining.
        Each arriving answer is PARKED per slot (a park row rides the
        recorder stream, so a crash loses nothing); when all ``acq_batch``
        slots are filled the park drains through ONE batch-label dispatch
        in slot order under a deterministic synthetic request_id — so an
        out-of-order delivery commits the exact bytes the in-order one
        does, and the dedupe cache makes redelivery of any answer (or of
        the fused round) idempotent. An ``abstain`` leaves its slot open.
        Injectable at the ``oracle_answer`` fault site (``oracle_poison``
        corrupts the label to the adversarial family, ``oracle_abstain``
        converts the answer into an abstention)."""
        self._check_hold(sid)
        sess = self._resolve_pinned(sid)
        to_dispatch = None
        round_idx = 0
        try:
            self._check_epoch(sess, epoch)
            if sess.restoring:
                raise BucketQuarantined(
                    f"session {sid} is being restored; retry shortly")
            if not sess.last:
                raise UnknownSession(sid)
            q = sess.bucket.acq_batch
            slot = int(slot)
            if not 0 <= slot < q:
                raise ValueError(
                    f"slot {slot} out of range [0, {q}) for session {sid}")
            fired = (self.faults.fire("oracle_answer", task=sess.task)
                     if self.faults is not None else [])
            if "oracle_abstain" in fired:
                abstain = True
            if not abstain:
                if label is None:
                    raise ValueError(
                        "missing 'label' (or set 'abstain': true)")
                label = int(label)
                if "oracle_poison" in fired:
                    label = (label + 1) % sess.bucket.n_classes
                    self.metrics.record_oracle("poisoned")
                if not 0 <= label < sess.bucket.n_classes:
                    raise ValueError(f"label {label} out of range "
                                     f"[0, {sess.bucket.n_classes})")
            round_idx = sess.n_labeled // q
            park_row = None
            with self.store.lock:
                if request_id is not None:
                    done = sess.recent.get(request_id)
                    if done is not None:
                        # the round this answer was part of has already
                        # committed — answer from the cached result, never
                        # re-apply (redelivery of a deferred answer)
                        out = self._payload(sess, dict(done))
                        out.update({"verb": "committed", "slot": slot,
                                    "duplicate": True})
                        return out
                missing = [j for j in range(q) if j not in sess.parked]
                if abstain:
                    self.metrics.record_oracle("abstain")
                    return {"session": sid, "verb": "abstain",
                            "slot": slot, "round": round_idx,
                            "parked": q - len(missing), "missing": missing}
                entry = sess.parked.get(slot)
                if entry is not None:
                    if request_id is not None and \
                            entry.get("request_id") == request_id:
                        return {"session": sid, "verb": "parked",
                                "slot": slot, "round": round_idx,
                                "duplicate": True,
                                "parked": q - len(missing),
                                "missing": missing}
                    self.metrics.record_oracle("double_apply_reject")
                    raise ValueError(
                        f"session {sid} round {round_idx} slot {slot} "
                        "already has a parked answer (duplicate delivery "
                        "refused)")
                # reorder depth: how many LATER slots arrived before this
                # one — the loadgen's deferred-delivery evidence
                depth = sum(1 for j in sess.parked if j > slot)
                seq = sess.park_seq
                sess.park_seq += 1
                sess.parked[slot] = {"label": label,
                                     "request_id": request_id, "seq": seq}
                self.metrics.record_oracle("parked", depth=depth)
                park_row = {"kind": "answer_park", "session": sid,
                            "round": round_idx, "slot": slot,
                            "label": label, "request_id": request_id,
                            "seq": seq}
                if len(sess.parked) == q:
                    to_dispatch = dict(sess.parked)
                    sess.parked = {}
            # stream the park OUTSIDE the store lock (disk write): the
            # row carries its slot + seq, so concurrent parks interleaving
            # in the file restore identically regardless of write order
            self.recorder.append(sid, park_row)
            if to_dispatch is None:
                with self.store.lock:
                    missing = [j for j in range(q) if j not in sess.parked]
                return {"session": sid, "verb": "parked", "slot": slot,
                        "round": round_idx, "parked": q - len(missing),
                        "missing": missing}
        finally:
            self.store.unpin(sess)
        # all q slots filled: drain through ONE fused dispatch in SLOT
        # order under a deterministic synthetic request_id — delivery
        # order is now immaterial, and a crashed/retried drain dedupes
        q = sess.bucket.acq_batch
        ordered = [to_dispatch[j]["label"] for j in range(q)]
        rid = f"answer:{sid}:{round_idx}"
        try:
            payload = self.label(sid, ordered if q > 1 else ordered[0],
                                 request_id=rid, epoch=epoch,
                                 trace_ctx=trace)
        except BaseException:
            # failed drain: re-park so the answers survive for a retry
            # (the park rows are still in the stream; nothing is lost)
            with self.store.lock:
                for j, e in to_dispatch.items():
                    sess.parked.setdefault(j, e)
            raise
        self.metrics.record_oracle("round_completed")
        with self.store.lock:
            done = sess.recent.get(rid)
            if done is not None:
                # every per-answer request_id now answers from the
                # committed round — late redelivery reads, never re-applies
                for e in to_dispatch.values():
                    if e.get("request_id"):
                        sess.recent[e["request_id"]] = done
        payload = dict(payload)
        payload.update({"verb": "dispatched", "slot": slot,
                        "round": round_idx, "applied": ordered})
        return payload

    async def answer_async(self, sid: str, slot, label=None,
                           request_id: Optional[str] = None,
                           epoch: Optional[int] = None,
                           abstain: bool = False, trace_ctx=None) -> dict:
        # parking is host-dict work but the drain dispatch blocks on the
        # batcher — always off the event loop (like the wake-through path)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: self.answer(sid, slot, label=label,
                                request_id=request_id, epoch=epoch,
                                abstain=abstain, trace_ctx=trace_ctx))

    def best(self, sid: str, epoch: Optional[int] = None,
             trace_ctx=None) -> dict:
        my = self._trace_child(trace_ctx)
        with self._serve_span("best", my):
            return self._best_impl(sid, epoch=epoch)

    def _best_impl(self, sid: str, epoch: Optional[int] = None) -> dict:
        self._check_hold(sid)
        sess = self._resolve_pinned(sid)  # wakes a parked session
        try:
            self._check_epoch(sess, epoch)
            if sess.restoring:
                # the slot holds a partially-replayed posterior and
                # n_labeled is still 0 — answering now would serve a wrong
                # best-model estimate with a 200; same retryable contract
                # as label
                raise BucketQuarantined(
                    f"session {sid} is being restored; retry shortly")
            out = self._payload(sess, sess.last or None)
            with sess.bucket.lock:
                pbest = sess.bucket.pbest(sess.slot)
            if pbest is not None:
                out["pbest"] = pbest.tolist()
            return out
        finally:
            self.store.unpin(sess)

    def close_session(self, sid: str, epoch: Optional[int] = None) -> dict:
        # a close landing in the migration-hold window would discard the
        # copy whose export is already in the router's hands — and the
        # import would then resurrect the "closed" session on the
        # destination. Retryable: the retry lands post-commit on the new
        # owner (and closes it there) or post-abort back here.
        self._check_hold(sid)
        try:
            sess = self.store.get(sid)
            self._check_epoch(sess, epoch)
            restoring = sess.restoring
        except UnknownSession:
            # a parked session closes without waking: drop the payload /
            # hibernate file and seal the stream (close marker)
            if self.tiers is not None and self.tiers.discard(sid):
                self.recorder.seal(sid)
                self.metrics.record_session("close")
                return {"closed": sid}
            raise
        if restoring:
            # freeing the slot mid-replay would let a new admission take
            # it while the restore keeps dispatching recorded rounds into
            # it — corrupting whichever session lands there
            raise BucketQuarantined(
                f"session {sid} is being restored; retry shortly")
        if self.prior_pool is not None and not sess.parked:
            # harvest the fit before the slot is freed (a parked session
            # already contributed at demotion)
            try:
                self.contribute_prior(sess, sess.bucket.slot_fit(sess.slot))
            except Exception:
                pass  # a close must never fail on pool bookkeeping
        if self.quality is not None and not sess.parked \
                and self.quality.should_audit(sid):
            # shadow audit: capture the stream and the session's seeding
            # facts BEFORE close tears them down; the replay itself runs
            # on the audit worker thread against a scratch slot
            try:
                rows = self.recorder.history(sid)
                if rows:
                    self.quality.maybe_enqueue_audit(
                        sess.bucket, sid, sess.seed, rows,
                        prior=sess.prior_fit, task=sess.task)
            except Exception:
                pass  # a close must never fail on audit bookkeeping
        self.store.close(sid)
        self.recorder.close(sid)
        if self.tiers is not None:
            self.tiers.discard(sid)  # clear any stale cold-index entry
        self.metrics.record_session("close")
        return {"closed": sid}

    def trace(self, sid: str, epoch: Optional[int] = None) -> dict:
        """The session's per-round decision history from its record stream
        (the flight recorder's interactive face: every dispatch this
        session rode, with the proposed item, best-model answer, and the
        label that was applied)."""
        self._check_hold(sid)
        sess = self._resolve_pinned(sid)  # wakes a parked session
        try:
            self._check_epoch(sess, epoch)
            if sess.restoring:
                # import_history lands only after the replay verifies; a
                # trace served now would be empty/partial, not the
                # session's history
                raise BucketQuarantined(
                    f"session {sid} is being restored; retry shortly")
            rounds = self.recorder.history(sid) or []
            return {"session": sid, "task": sess.task,
                    "n_labeled": sess.n_labeled, "rounds": rounds}
        finally:
            self.store.unpin(sess)

    def export_session(self, sid: str, close: bool = False,
                       hold: bool = False) -> dict:
        """The migration verb behind ``POST /session/{id}/export``: a
        self-contained payload (recorder stream + fingerprint-guarded
        carries snapshot) any same-task server can import. ``close`` frees
        the slot once the payload is built — the drain handoff.
        ``hold`` runs the fleet's PREPARE protocol instead (quiesce,
        hold, export-without-close — see :meth:`begin_migration`); the
        router commits or aborts through ``POST /session/{id}/fence``.

        A PARKED session exports without waking — its warm/cold payload
        IS the export (a demotion is an export minus the HTTP hop). The
        export pin means a demotion either completed before this verb
        (payload served from the tier) or cleanly aborts against it —
        the client always gets a consistent snapshot."""
        if hold:
            return self.begin_migration(sid)
        if close:
            # a closing export is a drain handoff: like close_session it
            # must wait out a migration hold, not race it
            self._check_hold(sid)
        from coda_tpu.serve import recovery
        from coda_tpu.serve.recovery import _counter

        try:
            sess = self.store.get_pinned(sid)
        except UnknownSession:
            payload = (self.tiers.parked_payload(sid)
                       if self.tiers is not None else None)
            if payload is None:
                raise
            if close:
                self.tiers.discard(sid)
                self.recorder.seal(sid)
                self.metrics.record_session("close")
            self.metrics.record_recovery("exported")
            _counter("serve_sessions_exported_total",
                     "Sessions serialized for checkpoint/migration").inc()
            return payload
        try:
            payload = recovery.export_session(self, sid)
        finally:
            self.store.unpin(sess)
        if close:
            self.close_session(sid)
        return payload

    def import_session(self, payload: dict) -> dict:
        """The restore verb behind ``POST /session/import``: admit the
        exported session under its ORIGINAL id (the client's handle
        survives the migration), restore its posterior via the
        digest-verified snapshot fast path or bitwise stream replay, and
        answer like a normal session verb."""
        from coda_tpu.serve import recovery

        if self.draining:
            self.metrics.record_session("reject")
            raise Draining()
        try:
            try:
                info = recovery.import_session(self, payload)
            except SlabFull:
                # tiering admission: an import past slab capacity demotes
                # the coldest resident session instead of 503
                if self.tiers is None or not self.tiers.make_room_for(
                        payload.get("task"), self.spec):
                    raise
                info = recovery.import_session(self, payload)
        except BaseException:
            # a restore replay dispatch that consumed donated carries
            # quarantines its bucket WITHOUT passing through the batcher's
            # failure hook (imports never ride a tick) — kick the heal
            # here so retried imports find a rebuilt slab, not a 503 loop
            self._heal_quarantined()
            raise
        sess = self.store.get(info["session"])
        out = self._payload(sess, sess.last or None)
        out.update(restored_via=info["restored_via"],
                   rounds=info["rounds"])
        return out

    def restore_sessions(self, record_dir: Optional[str] = None) -> dict:
        """Rebuild every un-closed session stream in ``record_dir`` (the
        crash-restart path; ``--restore`` runs it at startup)."""
        from coda_tpu.serve import recovery

        report = recovery.restore_app_sessions(self, record_dir)
        self._heal_quarantined()  # a failed restore replay must not leave
        return report             # a bucket 503-refused with no heal queued

    def _heal_quarantined(self) -> None:
        for b in self.store.buckets():
            if b.quarantined is not None:
                self.healer.schedule(b)

    def list_sessions(self) -> dict:
        """Every addressable session id across all tiers (the fleet
        router's rebalance worklist — ``GET /sessions``). Set-deduped:
        this runs per replica per topology change at 100k+-session
        scale."""
        with self.store.lock:
            sids = list(self.store._sessions)
        if self.tiers is not None:
            seen = set(sids)
            sids += [s for s in self.tiers.parked_sids()
                     if s not in seen]
        return {"sessions": sids}

    def healthz(self) -> dict:
        ready = self.ready.is_set()
        # three-state readiness for the load balancer: "unready" (warm
        # pool still compiling — take no traffic), "degraded" (serving,
        # but something needs attention: a failed/quarantined/lazy bucket,
        # a warm-up failure, or recorder streams downgraded to
        # memory-only), "ok". Degraded stays 200 — the process is live
        # and answering; the status string is the operator's signal.
        buckets = self.store.buckets()
        problems = []
        if self.warm_error:
            problems.append("warmup_failed")
        if any(b.failed is not None for b in buckets):
            problems.append("buckets_failed")
        if any(b.quarantined is not None for b in buckets):
            problems.append("buckets_quarantined")
        if self._warm_requested and any(not b.is_warm for b in buckets):
            problems.append("buckets_lazy")
        if getattr(self.recorder, "degraded_streams", 0):
            problems.append("recorder_degraded")
        status = ("unready" if not ready
                  else "degraded" if problems else "ok")
        return {"ok": ready and not self.draining, "ready": ready,
                "draining": self.draining, "status": status,
                "problems": problems}

    # -- cross-session surrogate prior (serve/priors.py) -------------------
    def contribute_prior(self, sess, fit_stats) -> bool:
        """Fold one session's fit statistics into the pool (at close or
        demotion; exactly once per session). A SEEDED session's inherited
        pool mass is subtracted first — the per-refold decay is linear,
        so what is left of the seed after ``fits`` refolds is exactly
        ``SURROGATE_FIT_DECAY ** fits`` of it; without the subtraction
        every generation would re-contribute its ancestors' statistics
        and the pool would amplify instead of track."""
        if self.prior_pool is None or fit_stats is None \
                or sess.prior_contributed:
            return False
        import numpy as np

        from coda_tpu.selectors.surrogate import (SURROGATE_FIT_DECAY,
                                                  prior_from_dict)
        from coda_tpu.serve.priors import bucket_pool_key

        if sess.prior_fit is not None:
            g = SURROGATE_FIT_DECAY ** float(
                np.asarray(fit_stats.get("fits", 0)))
            seed = prior_from_dict(sess.prior_fit)
            fit_stats = {
                "A": np.asarray(fit_stats["A"], np.float64) - g * seed.A,
                "b": np.asarray(fit_stats["b"], np.float64) - g * seed.b,
                "n": max(0.0, float(fit_stats["n"]) - g * seed.n),
                "rounds": fit_stats["rounds"],
            }
        ok = self.prior_pool.contribute(
            bucket_pool_key(self, sess.bucket), fit_stats)
        if ok:
            sess.prior_contributed = True
            self.refresh_bucket_priors()
        return ok

    def refresh_bucket_priors(self) -> int:
        """Re-resolve each bucket's admission prior from the pool (after
        a contribution, a router pool push, or a restart restore)."""
        if self.prior_pool is None:
            return 0
        from coda_tpu.serve.priors import bucket_pool_key

        n = 0
        for b in self.store.buckets():
            stats = self.prior_pool.get(bucket_pool_key(self, b))
            if b.set_prior(stats) is not None:
                n += 1
        return n

    def _prior_totals(self) -> dict:
        """ServeMetrics snapshot provider for the prior evidence triple
        (+ pool gauges): contributions accepted into the pool, warmup
        rounds the pool credited to live sessions (slab-read), and gate
        rejections that fired inside a credited warmup window."""
        if self.prior_pool is None:
            return {}
        per = getattr(self, "_surrogate_per", None)
        if per is None:
            per = {}
            for b in self.store.buckets():
                s = b.surrogate_stats()
                if s is not None:
                    per[id(b)] = s
        pool = self.prior_pool.stats()
        return {
            "prior_sessions_contributed": pool["sessions_contributed"],
            "prior_warmup_rounds_skipped": sum(
                s.get("prior_rounds", 0) for s in per.values()),
            "prior_gate_rejections": sum(
                s.get("prior_rejects", 0) for s in per.values()),
            "prior_pools": pool["pools"],
            "prior_rounds_pooled": pool["rounds_pooled"],
            # r20 staleness satellite: age of the least recently refreshed
            # pool (None until the first contribution lands) + per-pool
            # contribution ages — /metrics renders both
            "prior_pool_staleness_seconds": pool["staleness_seconds"],
            "prior_pool_ages_seconds": pool["pool_ages_seconds"],
        }

    def sync_prior(self, pool_snap: Optional[dict] = None) -> dict:
        """The router exchange verb (``POST /prior/sync``, piggybacked on
        the health poll): drain this replica's since-last-poll delta for
        the caller, adopt the router's merged pool when one is pushed,
        then re-fold the just-drained delta locally so this replica's own
        recent contributions stay live until the next push echoes them
        back (uncounted — contribute() already counted them)."""
        if self.prior_pool is None:
            return {"delta": {}}
        delta = self.prior_pool.drain_delta()
        if pool_snap:
            self.prior_pool.replace(pool_snap)
            if delta:
                self.prior_pool.merge_delta(delta, count=False)
            self.refresh_bucket_priors()
        return {"delta": delta}

    def save_prior_pool(self, tracking_store) -> Optional[str]:
        """Persist the pool into the tracking store (one stable named
        run, ``prior_pool.json`` artifact) — the restart-survival half."""
        if self.prior_pool is None:
            return None
        import json as _json

        with tracking_store.run("serve", "surrogate-prior-pool") as run:
            return run.log_artifact_bytes(
                "prior_pool.json",
                _json.dumps(self.prior_pool.snapshot()).encode())

    def load_prior_pool(self, tracking_store) -> int:
        """Adopt the persisted pool (restart path); returns pools loaded."""
        if self.prior_pool is None:
            return 0
        import json as _json

        found = tracking_store.find_run("serve", "surrogate-prior-pool")
        if not found:
            return 0
        path = os.path.join(tracking_store.artifact_root, found[0],
                            "prior_pool.json")
        try:
            with open(path) as f:
                snap = _json.load(f)
        except (OSError, ValueError):
            return 0
        n = self.prior_pool.replace(snap)
        self.refresh_bucket_priors()
        return n

    def _surrogate_totals(self) -> dict:
        """Aggregate surrogate counters over every surrogate-scorer
        bucket (ServeMetrics snapshot provider): rounds scored, contract
        fallbacks, fit refolds, and the worst (minimum) escape-gate
        margin — {} when no bucket runs the surrogate rung.

        Side effect: caches the per-bucket dicts on ``_surrogate_per``
        so ``stats()`` — which triggers this via its snapshot() call —
        reuses them for the per-bucket sections instead of taking every
        bucket's dispatch lock (and its device readback) a second time
        per request."""
        per_bucket = {}
        for b in self.store.buckets():
            s = b.surrogate_stats()
            if s is not None:
                per_bucket[id(b)] = s
        self._surrogate_per = per_bucket
        per = list(per_bucket.values())
        if not per:
            return {}
        margins = [s["contract_margin"] for s in per
                   if s["contract_margin"] is not None]
        return {
            "surrogate_rounds": sum(s["rounds"] for s in per),
            "surrogate_fallbacks": sum(s["fallbacks"] for s in per),
            "surrogate_fit_refreshes": sum(s["fit_refreshes"]
                                           for s in per),
            "surrogate_contract_margin": (min(margins) if margins
                                          else None),
        }

    def stats(self) -> dict:
        # refresh the tier occupancy FIRST so the snapshot below carries
        # current gauges even between sweeper passes
        if self.tiers is not None:
            tiers = self.tiers.counts()
            self.metrics.set_tier_occupancy(**tiers)
        else:
            tiers = {"hot": self.store.live_sessions(), "warm": 0,
                     "cold": 0}
        snap = self.metrics.snapshot()
        # open sessions vs slab occupancy are DISTINCT the moment a
        # session can live off-slab: open = every addressable session
        # across all three tiers, occupancy = live device slab slots
        snap["open_sessions"] = tiers["hot"] + tiers["warm"] + tiers["cold"]
        snap["slab_occupancy"] = self.store.slab_occupancy()
        snap["draining"] = self.draining
        snap["ready"] = self.ready.is_set()
        # flight-recorder evidence, in distinct units: run RECORDS written
        # process-wide (registry counter) vs per-dispatch decision ROWS
        # this server streamed — plus the replay counter (a replay running
        # in-process shows up here next to the serving numbers)
        reg = self.telemetry.registry
        snap["records_written"] = int(
            reg.counter("records_written_total").value())
        snap["record_rows_written"] = int(self.recorder.rows_written)
        snap["replay_verified"] = int(
            reg.counter("replay_verified_total").value())
        snap["buckets"] = [
            {"task": b.task, "method": b.spec.method,
             "shape": list(b.shape), "capacity": b.capacity, "live": b.live,
             "warm": b.is_warm, "warm_s": b.warm_s,
             "warm_hits": b.warm_hits, "warm_misses": b.warm_misses,
             "failed": b.failed, "quarantined": b.quarantined,
             "heals": b.heals,
             # the warm pool's XLA cost attribution per program (step/
             # init/pbest/write_slot): FLOPs, bytes accessed, peak
             # device-resident bytes, roofline class — populated by
             # warm(), empty before it (or where cost_analysis is
             # unavailable)
             "cost": dict(b.cost_info),
             # surrogate-scorer evidence (None for exact-scorer buckets):
             # rounds / contract fallbacks / fit refolds / worst margin —
             # read from the snapshot provider's per-request cache (the
             # snapshot() call above just refreshed it), never a second
             # bucket-lock/device-read pass
             "surrogate": getattr(self, "_surrogate_per", {}).get(id(b))}
            for b in self.store.buckets()
        ]
        if self.prior_pool is not None:
            snap["prior_pool"] = self.prior_pool.stats()
        if self.quality is not None:
            # fold THIS pass's live signals (surrogate gate pressure,
            # prior staleness-regret) into the drift detectors, then
            # re-read the plane so the snapshot reflects the fold it
            # just caused rather than lagging one /stats pass behind
            self.quality.feed_serve_stats(snap["buckets"], snap)
            snap["quality"] = self.quality.snapshot()
        snap["warm_error"] = self.warm_error
        snap["recorder_degraded_streams"] = int(
            getattr(self.recorder, "degraded_streams", 0))
        snap["status"] = self.healthz()["status"]
        if self.faults is not None:
            snap["faults"] = self.faults.snapshot()
        return snap

    def quality_scorecard(self) -> Optional[dict]:
        """``GET /fleet/quality`` on a single replica: this plane's
        scorecard (the fleet router overrides this with the per-replica
        aggregate). None with ``--no-quality`` — the route 404s."""
        if self.quality is None:
            return None
        return self.quality.scorecard()

    def _payload(self, sess, res: Optional[dict]) -> dict:
        out = {
            "session": sess.sid,
            "task": sess.task,
            "n_labeled": sess.n_labeled,
        }
        if res:
            out.update({
                "idx": res["next_idx"],
                "prob": res["next_prob"],
                "best": res["best"],
                "stochastic": res["stochastic"],
            })
        return out


class Draining(RuntimeError):
    """New sessions refused: the server is shutting down."""


class StaleItem(ValueError):
    """The labeled idx is not the item the session proposed."""


_SESSION_RE = re.compile(
    r"^/session/([0-9a-f]+)"
    r"(/(label|labels|answer|best|trace|export|fence|epoch))?$")

# GET /trace/id/{trace_id}: retained distributed-trace spans (distinct
# from GET /session/{id}/trace, the per-round DECISION history)
_TRACE_ID_RE = re.compile(r"^/trace/id/([0-9a-f]+)$")

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            409: "Conflict", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout"}

_JSON = "application/json"
_PROM = "text/plain; version=0.0.4; charset=utf-8"

# idle keep-alive connections are reaped after this many seconds so a
# slow-loris client can't pin loop resources forever
_IDLE_TIMEOUT_S = 120.0

# request bodies are small JSON (a label, a seed); a client declaring more
# than this is broken or hostile and must not make the loop buffer it
_MAX_BODY_BYTES = 1 << 20


class AsyncHTTPServer:
    """Asyncio front door with the stdlib server's surface.

    The listening socket binds at construction (``server_address`` is
    immediately readable, ``port=0`` picks a free port — the test hook);
    ``serve_forever()`` runs the event loop in the calling thread;
    ``shutdown()`` (any thread) stops it and blocks until it has;
    ``server_close()`` releases the socket. Drop-in for the
    ``ThreadingHTTPServer`` it replaced, so embedders and tests are
    unchanged.

    The protocol half is deliberately minimal HTTP/1.1 — request line,
    headers, Content-Length bodies, keep-alive — which is all the JSON API
    (and every stdlib/urllib client) needs, and keeps the no-new-deps
    stance of the rest of the stack.
    """

    def __init__(self, app: ServeApp, port: int = 0,
                 host: str = "127.0.0.1"):
        self.app = app
        self._sock = socket.create_server((host, port), backlog=512)
        self.server_address = self._sock.getsockname()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop: Optional[asyncio.Event] = None
        self._started = threading.Event()
        self._finished = threading.Event()
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        server = await asyncio.start_server(self._serve_conn,
                                            sock=self._sock)
        self._started.set()
        try:
            await self._stop.wait()
        finally:
            server.close()
            try:
                await server.wait_closed()
            except Exception:
                pass
            self._closed = True
            self._finished.set()

    def shutdown(self) -> None:
        loop, stop = self._loop, self._stop
        if loop is not None and stop is not None and loop.is_running():
            loop.call_soon_threadsafe(stop.set)
            self._finished.wait(timeout=30.0)

    def server_close(self) -> None:
        if not self._closed:
            try:
                self._sock.close()
            except OSError:
                pass
            self._closed = True

    # -- one connection ----------------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    line = await asyncio.wait_for(reader.readline(),
                                                  _IDLE_TIMEOUT_S)
                except asyncio.TimeoutError:
                    break
                if not line or line in (b"\r\n", b"\n"):
                    break
                parts = line.decode("latin1").split()
                if len(parts) != 3:
                    break
                method, target, version = parts
                headers = {}
                while True:
                    h = await reader.readline()
                    if not h or h in (b"\r\n", b"\n"):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                try:
                    n = int(headers.get("content-length") or 0)
                except ValueError:
                    n = -1
                if 0 <= n <= _MAX_BODY_BYTES:
                    body = await reader.readexactly(n) if n > 0 else b""
                    status, payload, ctype = await self._handle(
                        method, target, body, headers)
                else:
                    # malformed or oversized Content-Length: answer a JSON
                    # error (never a dropped connection) and close — the
                    # unread body makes the stream unusable for keep-alive
                    headers["connection"] = "close"
                    status, payload, ctype = (
                        400, {"error": "bad request: invalid or oversized "
                                       "Content-Length"}, _JSON)
                data = (payload.encode() if isinstance(payload, str)
                        else json.dumps(payload).encode())
                keep = (version == "HTTP/1.1"
                        and headers.get("connection", "").lower() != "close")
                head = (f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}\r\n"
                        f"Content-Type: {ctype}\r\n"
                        f"Content-Length: {len(data)}\r\n"
                        "Connection: "
                        f"{'keep-alive' if keep else 'close'}\r\n\r\n")
                writer.write(head.encode("latin1") + data)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away mid-request; nothing to answer
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    # -- routing (same error envelope as the session verbs raise) ----------
    async def _handle(self, method: str, target: str, body: bytes,
                      headers: Optional[dict] = None):
        app = self.app
        path, _, query = target.partition("?")
        params = {}
        for kv in filter(None, query.split("&")):
            k, _, v = kv.partition("=")
            params[k] = v
        # trace context: continue the caller's (`coda-trace` header), or
        # mint fresh at this front door for session verbs so every label
        # decision has ONE causal trace even from untraced clients. Never
        # touches session state — purely observational.
        trace_ctx = None
        if getattr(app, "tracing", False):
            from coda_tpu.telemetry.trace import TRACE_HEADER, mint, parse

            trace_ctx = parse((headers or {}).get(TRACE_HEADER, ""))
            if trace_ctx is None and path.startswith("/session"):
                trace_ctx = mint()
        if method == "GET" and path == "/healthz":
            # the readiness gate: 503 until the warm pool is compiled, so
            # a restarting replica takes no traffic while executables are
            # still being built/deserialized. Draining stays 200 — the
            # process is live and still answering existing sessions.
            h = app.healthz()
            return (200 if h["ready"] else 503), h, _JSON
        if method == "GET" and path == "/metrics":
            # Prometheus text exposition, not JSON: registry counters
            # (recompiles, cache hits/misses, HBM watermarks) + the serve
            # snapshot (dispatches, occupancy, queue depth, latency
            # quantiles, warm pool). Same error envelope as every other
            # route: a render failure must answer a JSON 500, never drop
            # the connection.
            try:
                from coda_tpu.telemetry import render_prometheus

                # a fleet router merges every replica's families with
                # per-replica labels (render_metrics); a single replica
                # renders its own registry + serve snapshot
                if hasattr(app, "render_metrics"):
                    render = app.render_metrics
                else:
                    def render():
                        return render_prometheus(
                            app.telemetry.registry,
                            serve_metrics=app.metrics)
                text = await asyncio.get_running_loop().run_in_executor(
                    None, render)
            except Exception as e:
                return 500, {"error": f"internal: {e}"}, _JSON
            return 200, text, _PROM
        try:
            out = await self._route(method, path, body, params,
                                    trace_ctx=trace_ctx)
        except Draining:
            return (503, {"error": "draining: not admitting new sessions"},
                    _JSON)
        except SlabFull as e:
            return 503, {"error": f"busy: {e}"}, _JSON
        except BucketQuarantined as e:
            # the slab is being rebuilt from session streams — transient,
            # retryable: 503 like every other backpressure signal
            return 503, {"error": f"healing: {e}"}, _JSON
        except StaleOwner as e:
            # the fencing rejection: this replica's copy is stale — the
            # router re-locates on this envelope; a direct client should
            # re-resolve the fleet front door
            app.metrics.record_session("request_reject")
            return 409, {"error": f"stale owner: {e}"}, _JSON
        except ImportRejected as e:
            return 409, {"error": f"import rejected: {e}"}, _JSON
        except UnknownSession as e:
            app.metrics.record_session("request_reject")
            return 404, {"error": f"unknown session {e}"}, _JSON
        except StaleItem as e:
            app.metrics.record_session("request_reject")
            return 409, {"error": str(e)}, _JSON
        except TimeoutError as e:
            return 504, {"error": str(e)}, _JSON
        except (ValueError, TypeError, KeyError) as e:
            return 400, {"error": f"bad request: {e}"}, _JSON
        except Exception as e:  # cancelled tickets, dispatch failures: the
            # client must get a JSON error, never a dropped connection
            return 500, {"error": f"internal: {e}"}, _JSON
        if out is None:
            return 404, {"error": "not found"}, _JSON
        return 200, out, _JSON

    async def _route(self, method: str, path: str, raw: bytes,
                     params: Optional[dict] = None, trace_ctx=None):
        app = self.app
        loop = asyncio.get_running_loop()
        m = _SESSION_RE.match(path)

        def _epoch(req=None):
            # the router's fencing stamp: body field on POST/DELETE,
            # ?epoch=N on GETs
            v = (req or {}).get("epoch")
            if v is None:
                v = (params or {}).get("epoch")
            return None if v in (None, "") else int(v)

        if method == "POST" and path == "/session/import":
            # restore an exported session (replay/snapshot verification is
            # real compute — never on the event loop)
            req = json.loads(raw or b"{}")
            return await loop.run_in_executor(app._executor,
                                              app.import_session, req)
        if method == "POST" and path == "/session":
            req = json.loads(raw or b"{}")
            kw = {}
            if req.get("session") is not None:
                # a fleet router pins the id (rendezvous placement)
                kw["sid"] = str(req["session"])
            return await app.open_session_async(task=req.get("task"),
                                                seed=req.get("seed"),
                                                trace_ctx=trace_ctx, **kw)
        if m and method == "POST" and m.group(3) == "label":
            req = json.loads(raw or b"{}")
            if "label" not in req:
                raise ValueError("missing 'label'")
            return await app.label_async(m.group(1), req["label"],
                                         idx=req.get("idx"),
                                         request_id=req.get("request_id"),
                                         epoch=_epoch(req),
                                         trace_ctx=trace_ctx)
        if m and method == "POST" and m.group(3) == "labels":
            # batch of oracle answers, one dispatch (see ServeApp.labels)
            req = json.loads(raw or b"{}")
            if not isinstance(req.get("labels"), list) or not req["labels"]:
                raise ValueError("missing non-empty 'labels' list")
            return await app.labels_async(m.group(1), req["labels"],
                                          idx=req.get("idx"),
                                          request_id=req.get("request_id"),
                                          epoch=_epoch(req),
                                          trace_ctx=trace_ctx)
        if m and method == "POST" and m.group(3) == "answer":
            # one per-slot crowd answer, any order (see ServeApp.answer)
            req = json.loads(raw or b"{}")
            if "slot" not in req:
                raise ValueError("missing 'slot'")
            if "label" not in req and not req.get("abstain"):
                raise ValueError("missing 'label' (or 'abstain': true)")
            return await app.answer_async(m.group(1), req["slot"],
                                          label=req.get("label"),
                                          request_id=req.get("request_id"),
                                          epoch=_epoch(req),
                                          abstain=bool(req.get("abstain")),
                                          trace_ctx=trace_ctx)
        if m and method == "POST" and m.group(3) == "export":
            req = json.loads(raw or b"{}")
            return await loop.run_in_executor(
                app._executor,
                lambda: app.export_session(m.group(1),
                                           close=bool(req.get("close")),
                                           hold=bool(req.get("hold"))))
        if m and method == "POST" and m.group(3) == "fence":
            # the migration commit/abort half of the hold protocol
            req = json.loads(raw or b"{}")
            return await loop.run_in_executor(
                app._executor,
                lambda: app.end_migration(m.group(1),
                                          drop=bool(req.get("drop"))))
        if m and method == "GET" and m.group(3) == "epoch":
            return await loop.run_in_executor(
                app._executor, app.session_epoch, m.group(1))
        if m and method == "GET" and m.group(3) == "best":
            return await loop.run_in_executor(
                app._executor,
                lambda: app.best(m.group(1), epoch=_epoch(),
                                 trace_ctx=trace_ctx))
        if m and method == "GET" and m.group(3) == "trace":
            return await loop.run_in_executor(
                app._executor,
                lambda: app.trace(m.group(1), epoch=_epoch()))
        if m and method == "DELETE" and m.group(3) is None:
            req = json.loads(raw or b"{}")
            return await loop.run_in_executor(
                app._executor,
                lambda: app.close_session(m.group(1), epoch=_epoch(req)))
        if method == "POST" and path == "/prior/sync":
            # the router's pool-exchange half of the health poll: push the
            # merged pool, collect this replica's contribution delta
            req = json.loads(raw or b"{}")
            return await loop.run_in_executor(
                app._executor, lambda: app.sync_prior(req.get("pool")))
        if method == "GET" and path == "/stats":
            return await loop.run_in_executor(app._executor, app.stats)
        if method == "GET" and path == "/sessions":
            return await loop.run_in_executor(app._executor,
                                              app.list_sessions)
        tm = _TRACE_ID_RE.match(path)
        if tm and method == "GET":
            # one causal trace by id: a fleet router stitches every
            # process's retained spans into one Chrome/Perfetto file
            # (collect_trace); a single replica serves its own raw
            # payload for such a collector to stitch
            if hasattr(app, "collect_trace"):
                return await loop.run_in_executor(
                    app._executor, app.collect_trace, tm.group(1))
            return await loop.run_in_executor(
                app._executor, app.trace_by_id, tm.group(1))
        if method == "GET" and path == "/fleet/slo" and \
                hasattr(app, "slo_snapshot"):
            # the SLO watchtower (router only): objectives, burn rates,
            # firing state, recent alerts
            return await loop.run_in_executor(app._executor,
                                              app.slo_snapshot)
        if method == "GET" and path == "/fleet/quality":
            # the decision-quality scorecard: a router aggregates its
            # replicas' planes; a single replica grades its own
            scorecard = getattr(app, "quality_scorecard", None)
            if scorecard is not None:
                return await loop.run_in_executor(app._executor, scorecard)
        return None


def make_server(app: ServeApp, port: int = 0,
                host: str = "127.0.0.1") -> AsyncHTTPServer:
    """Bind the front door; ``port=0`` picks a free port (for tests)."""
    return AsyncHTTPServer(app, port=port, host=host)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="batched multi-session serving of interactive active "
                    "model selection")
    p.add_argument("--task", default=None)
    p.add_argument("--data-dir", default="data")
    p.add_argument("--synthetic", default=None, metavar="H,N,C",
                   help="serve a seeded synthetic task of this shape")
    p.add_argument("--method", default="coda",
                   help="selector behind every session "
                        "{coda, iid, uncertainty, model_picker, ...}")
    p.add_argument("--acq-batch", type=int, default=1, metavar="Q",
                   help="labels per round per session (default 1). Q > 1 "
                        "sessions propose Q items per round and accept "
                        "all Q oracle answers through ONE "
                        "POST /session/{id}/labels dispatch (fused "
                        "multi-row posterior update) — the serving face "
                        "of --acq-batch")
    p.add_argument("--eig-scorer", default="exact",
                   metavar="exact|surrogate:k",
                   help="coda methods only: the scoring rung every "
                        "session's bucket compiles (the serving face of "
                        "the main CLI's --eig-scorer) — surrogate:k "
                        "amortizes the per-round scoring pass behind the "
                        "measured contract; surrogate counters surface "
                        "on /stats and /metrics. NOTE: amortizes only "
                        "under the 'map' slab lowering (the CPU "
                        "default); the 'vmap' lowering executes both "
                        "branches of the fallback cond per slot, so on "
                        "TPU/GPU slabs the rung is strictly slower than "
                        "exact (a one-time warning says so at bucket "
                        "build)")
    p.add_argument("--surrogate-prior", default="off",
                   choices=["off", "pool"],
                   help="coda + surrogate scorer only: warm-start every "
                        "session's surrogate fit from the cross-session "
                        "prior pool (serve/priors.py) — closed/demoted "
                        "sessions contribute their fit statistics, new "
                        "admissions seed from the merged pool and skip "
                        "already-paid exact warmup rounds; the per-round "
                        "trust gate is unchanged, so a selection is never "
                        "driven by an unaudited score. 'off' (default) is "
                        "bitwise-identical to the pre-pool behavior. With "
                        "--tracking-db the pool survives restarts; in a "
                        "fleet, replicas exchange pool deltas through the "
                        "router's health poll")
    p.add_argument("--capacity", type=int, default=64,
                   help="slab slots per bucket = max HOT (resident) "
                        "sessions per (task, config); admission past it "
                        "demotes the coldest session to the warm tier "
                        "(503 only with --no-tiering or when nothing is "
                        "demotable)")
    p.add_argument("--no-tiering", action="store_true",
                   help="disable hot/warm/cold session paging: sessions "
                        "exist only while they hold a slab slot and "
                        "admission past capacity answers 503 (the "
                        "pre-tiering behavior)")
    p.add_argument("--tier-spill-dir", default=None,
                   help="enable the COLD tier: idle warm payloads "
                        "hibernate to hibernated_<sid>.json files here "
                        "(scanned at startup, so cold sessions survive "
                        "restarts); without it paging is warm-only")
    p.add_argument("--idle-warm-s", type=float, default=30.0,
                   help="demote a hot session to the warm tier after this "
                        "many seconds without a request")
    p.add_argument("--idle-cold-s", type=float, default=120.0,
                   help="hibernate a warm session to the cold tier after "
                        "this many further idle seconds (needs "
                        "--tier-spill-dir)")
    p.add_argument("--max-warm", type=int, default=8192,
                   help="bound on host-RAM warm payloads; LRU overflow "
                        "hibernates to the cold tier (the RSS lever)")
    p.add_argument("--tier-free-frac", type=float, default=0.0,
                   help="sweeper keeps this fraction of each slab free by "
                        "demoting LRU-idle sessions ahead of admission "
                        "bursts (0 = demote only under admission "
                        "pressure)")
    p.add_argument("--bucket-n", type=int, default=1,
                   help="pad task N up to this quantum so near-shaped tasks "
                        "share one compiled program (1 = exact shapes)")
    p.add_argument("--max-batch", type=int, default=256,
                   help="max requests coalesced into one dispatch")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="linger after the first queued request before "
                        "dispatching, when the batcher was IDLE at arrival "
                        "(after a busy tick the next starts immediately — "
                        "continuous batching)")
    p.add_argument("--max-linger-ms", type=float, default=None,
                   help="hard cap on one tick's total formation window "
                        "regardless of arrival pattern "
                        "(default 4x --max-wait-ms)")
    p.add_argument("--step-impl", default=None,
                   choices=["map", "vmap"],
                   help="slab-step lowering: 'map' keeps bitwise parity "
                        "with the sequential reference (CPU default), "
                        "'vmap' feeds the slot axis to the parallel units "
                        "(TPU/GPU default)")
    p.add_argument("--no-donate", action="store_true",
                   help="keep the per-tick slab copy instead of donating "
                        "the carry buffers to the step (debug/parity aid)")
    p.add_argument("--no-warm", action="store_true",
                   help="skip the AOT warm pool: first dispatch per bucket "
                        "pays lazy jit compilation (readiness is immediate)")
    p.add_argument("--compilation-cache-dir", default=None,
                   help="persistent jax compilation cache: warm-pool "
                        "executables serialize here, so a restarted server "
                        "deserializes instead of recompiling")
    p.add_argument("--port", type=int, default=7861)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (cpu/tpu) — same as main.py")
    p.add_argument("--tracking-db", default=None,
                   help="flush serving metrics into this MLflow-schema "
                        "sqlite DB on shutdown")
    p.add_argument("--telemetry-dir", default=None,
                   help="write trace.json (Perfetto spans: batcher ticks + "
                        "slab steps) + telemetry.json (recompiles, cache "
                        "hits, HBM watermarks) + metrics.prom there on "
                        "shutdown; /metrics serves the same registry live "
                        "either way")
    p.add_argument("--record-dir", default=None,
                   help="stream each session's per-round decision history "
                        "to an append-only session_<id>.jsonl there "
                        "(crash-safe: every completed dispatch is flushed); "
                        "GET /session/{id}/trace serves the same stream "
                        "live either way")
    p.add_argument("--restore", action="store_true",
                   help="at startup, rebuild every un-closed session "
                        "stream found in --record-dir by bitwise replay "
                        "(the crash-restart path: a SIGKILLed server "
                        "restarted with --restore resumes its sessions)")
    p.add_argument("--no-trace", action="store_true",
                   help="disable distributed tracing (trace-context "
                        "propagation, serve/tick/step trace spans, "
                        "latency exemplars, GET /trace/id/{trace_id}). "
                        "Tracing never perturbs session math — on and "
                        "off produce bitwise-identical trajectories — "
                        "so this is purely an overhead lever")
    p.add_argument("--no-quality", action="store_true",
                   help="disable the decision-quality plane "
                        "(telemetry/quality.py): streaming calibration of "
                        "the served posterior, drift detectors, and the "
                        "shadow auditor that bitwise-replays a sample of "
                        "closed sessions. The plane never perturbs "
                        "session math — on and off produce "
                        "bitwise-identical decision rows — so this is "
                        "purely an overhead lever")
    p.add_argument("--quality-audit-frac", type=float, default=0.25,
                   help="fraction of closing sessions the shadow auditor "
                        "re-replays (deterministic per-sid hash sample; "
                        "0 disables auditing but keeps calibration/drift)")
    p.add_argument("--slo-fast-s", type=float, default=300.0,
                   help="SLO watchtower fast burn-rate window (seconds); "
                        "fleet router only")
    p.add_argument("--slo-slow-s", type=float, default=3600.0,
                   help="SLO watchtower slow burn-rate window (seconds); "
                        "fleet router only")
    p.add_argument("--fault-spec", default=None, metavar="SPEC",
                   help="deterministic fault injection (serve/faults.py): "
                        "'name:param=v,...[;name:...]' with names "
                        "step_raise | step_nan | record_eio | slow_step | "
                        "crash_before_tick | crash_after_tick and triggers "
                        "after=N / every=N / p=F,seed=S (e.g. "
                        "'step_raise:after=100') — exercises the recovery "
                        "paths under real traffic")
    return p.parse_args(argv)


def build_app(args) -> ServeApp:
    """ServeApp from parsed args (shared with the load generator)."""
    from coda_tpu.cli import load_dataset
    from coda_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache(getattr(args, "compilation_cache_dir", None))
    spec_kwargs = {}
    if args.method.startswith("coda"):
        # every slot carries its own incremental cache; the auto eig_mode
        # budget must see the whole slab (cli.py sets the same hint from
        # the seed-vmap width)
        spec_kwargs["n_parallel"] = args.capacity
        scorer = getattr(args, "eig_scorer", "exact")
        if scorer != "exact":
            spec_kwargs["eig_scorer"] = scorer
        prior_knob = getattr(args, "surrogate_prior", "off")
        if prior_knob and prior_knob != "off":
            # rides the spec so every bucket (and the recorder's knob
            # row) sees the mode; the pool fingerprint excludes it
            spec_kwargs["surrogate_prior"] = prior_knob
    telemetry = None
    if getattr(args, "telemetry_dir", None):
        from coda_tpu.telemetry import Telemetry

        telemetry = Telemetry(out_dir=args.telemetry_dir)
    recorder = None
    if getattr(args, "record_dir", None):
        from coda_tpu.telemetry import SessionRecorder

        recorder = SessionRecorder(out_dir=args.record_dir)
    max_linger_ms = getattr(args, "max_linger_ms", None)
    app = ServeApp(
        capacity=args.capacity, bucket_n=args.bucket_n,
        max_batch=args.max_batch, max_wait=args.max_wait_ms / 1e3,
        max_linger=(None if max_linger_ms is None else max_linger_ms / 1e3),
        spec=SelectorSpec.create(args.method,
                                 acq_batch=getattr(args, "acq_batch", 1),
                                 **spec_kwargs),
        step_impl=getattr(args, "step_impl", None),
        donate=not getattr(args, "no_donate", False),
        telemetry=telemetry, recorder=recorder,
        fault_spec=getattr(args, "fault_spec", None),
        tiering=not getattr(args, "no_tiering", False),
        tier_spill_dir=getattr(args, "tier_spill_dir", None),
        idle_warm_s=getattr(args, "idle_warm_s", 30.0),
        idle_cold_s=getattr(args, "idle_cold_s", 120.0),
        max_warm=getattr(args, "max_warm", 8192),
        tier_free_fraction=getattr(args, "tier_free_frac", 0.0),
        tracing=not getattr(args, "no_trace", False),
        quality=not getattr(args, "no_quality", False),
        quality_audit_frac=getattr(args, "quality_audit_frac", 0.25),
    )
    if args.task or args.synthetic:
        ds = load_dataset(args)
        app.add_task(ds.name, ds.preds, class_names=ds.class_names)
    else:
        from coda_tpu.data import make_synthetic_task

        task = make_synthetic_task(seed=0, H=8, N=512, C=10)
        app.add_task(task.name, task.preds)
    return app


def main(argv=None):
    args = parse_args(argv)
    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    app = build_app(args)
    if app.prior_pool is not None and args.tracking_db:
        # adopt the persisted pool BEFORE any admission so the first
        # session of this process already warm-starts
        from coda_tpu.tracking import TrackingStore

        _ts = TrackingStore(args.tracking_db)
        n = app.load_prior_pool(_ts)
        _ts.close()
        if n:
            print(f"surrogate prior pool restored: {n} pool(s) from "
                  f"{args.tracking_db}")
    if args.restore and args.record_dir:
        # crash restore BEFORE taking traffic: rebuild every un-closed
        # session stream (bitwise replay-verified), then open the doors
        app.start(warm=not args.no_warm)   # restore wants warm executables
        report = app.restore_sessions(args.record_dir)
        print(f"restored {len(report['restored'])} session(s) from "
              f"{args.record_dir} "
              f"({report['skipped_closed']} closed, "
              f"{len(report['failed'])} failed"
              + (f": {report['failed']}" if report["failed"] else "") + ")")
    else:
        # warm in the background so the socket binds immediately and
        # /healthz gates traffic until the pool is compiled/deserialized
        app.start(warm=not args.no_warm, warm_async=True)
    srv = make_server(app, args.port)
    print(f"serving {app.default_task!r} ({app.spec.method}) on "
          f"http://127.0.0.1:{srv.server_address[1]}/ — capacity "
          f"{app.store.capacity} sessions/bucket"
          + ("" if args.no_warm else "; warming pool (healthz 503 until "
             "ready)"))
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        print("\ndraining...")
    finally:
        app.drain()
        srv.server_close()
        if args.telemetry_dir:
            paths = app.telemetry.write(
                extra={"serve": app.metrics.snapshot()})
            print(f"telemetry written to {paths.get('telemetry')}")
        if args.tracking_db:
            from coda_tpu.tracking import TrackingStore

            store = TrackingStore(args.tracking_db)
            app.metrics.log_to_store(store, params={
                "method": app.spec.method,
                "capacity": app.store.capacity})
            if app.quality is not None:
                # the shutdown quality scorecard next to the metrics
                # rows (experiment serve_quality)
                app.quality.log_to_store(store, params={
                    "method": app.spec.method})
            if app.prior_pool is not None:
                app.save_prior_pool(store)  # the restart-survival half
            store.close()
            print(f"metrics logged to {args.tracking_db}")


if __name__ == "__main__":
    main()
