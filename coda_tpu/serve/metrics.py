"""Serving metrics: per-dispatch counters and latency percentiles.

The serving layer's one hot loop is the batcher tick (drain queue -> build
masked inputs -> one compiled slab step), so the metrics that matter are
per-dispatch: how many requests rode each program launch (batch occupancy —
the whole point of the subsystem), how deep the queue ran, and how long a
request waited end-to-end. Everything is recorded into fixed-size rings on
the host — O(1) per event, no allocation in the request path — and reduced
to percentiles only when a snapshot is asked for (the ``/stats`` endpoint,
or an end-of-run flush into the MLflow-schema tracking store).
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np

# ring capacity: big enough that p99 over the recent window is stable, small
# enough that a snapshot reduction is microseconds
_RING = 4096


def _percentiles(ring) -> dict:
    """{p50, p99, mean, max} of a ring of seconds, as milliseconds."""
    if not ring:
        return {"p50_ms": None, "p99_ms": None, "mean_ms": None,
                "max_ms": None}
    a = np.asarray(ring, np.float64) * 1e3
    return {
        "p50_ms": float(np.percentile(a, 50)),
        "p99_ms": float(np.percentile(a, 99)),
        "mean_ms": float(a.mean()),
        "max_ms": float(a.max()),
    }


class ServeMetrics:
    """Thread-safe counters + latency rings for the serving layer."""

    def __init__(self):
        self._lock = threading.Lock()
        # monotonic baseline: uptime is a DURATION, and wall clock jumps
        # (NTP slew, suspend) must not produce negative or inflated uptimes
        self.started = time.monotonic()
        # monotonically increasing counters
        self.dispatches = 0
        self.requests = 0
        self.sessions_opened = 0
        self.sessions_closed = 0
        self.sessions_rejected = 0   # admission-control refusals (slab full)
        self.requests_rejected = 0   # draining / bad-session refusals
        self.fencing_rejections = 0  # stale-epoch verbs refused (StaleOwner)
        # warm pool: AOT-precompiled executables vs lazy-jit fallbacks
        self.warm_hits = 0           # dispatches served by an AOT executable
        self.warm_misses = 0         # dispatches that fell back to lazy jit
        self.warm_pool_size = 0      # precompiled executables in the pool
        self.warm_pool_seconds = None  # warm-up wall time (None = no warm)
        # tiered posterior state (serve/tiering.py): paging events and the
        # hot/warm/cold occupancy gauges, plus a wake-latency ring — the
        # "wake-from-warm p99 under one batcher tick" claim's evidence
        self.demotions = 0           # hot -> warm (slab slot freed)
        self.hibernates = 0          # warm -> cold (payload spilled to disk)
        self.peer_pages = 0          # warm -> a less-loaded peer replica
        self.wakes = 0               # warm/cold -> hot (transparent restore)
        self.wakes_from_warm = 0
        self.wakes_from_cold = 0
        self.wakes_via_replay = 0    # digest mismatch -> stream replay path
        self.wake_failures = 0       # wakes that raised (payload re-parked)
        self.tier_occupancy = {"hot": 0, "warm": 0, "cold": 0}
        self._wake_s = collections.deque(maxlen=_RING)
        # fault tolerance: checkpoint/restore + bucket self-healing events
        self.recovery = {
            "exported": 0,     # sessions serialized for migration
            "imported": 0,     # sessions restored from export payloads
            "restored": 0,     # sessions rebuilt from streams after crash
            "quarantined": 0,  # buckets quarantined by a step failure
            "healed": 0,       # slab rebuilds that digest-verified
            "heal_failed": 0,  # rebuilds degraded to terminal
        }
        # gauges / rings
        self.max_occupancy = 0       # most requests ever served by one dispatch
        self._occupancy = collections.deque(maxlen=_RING)   # reqs per dispatch
        self._queue_depth = collections.deque(maxlen=_RING)  # at tick start
        self._dispatch_s = collections.deque(maxlen=_RING)  # dispatch wall
        self._step_s = collections.deque(maxlen=_RING)      # slab-step exec
        self._request_s = collections.deque(maxlen=_RING)   # submit->result
        self._queue_wait_s = collections.deque(maxlen=_RING)  # submit->tick
        # crowd-oracle answer path (POST /session/{id}/answer): per-slot
        # parking, abstentions, fault-injected poisons, and the async
        # delivery evidence — how many rounds completed out of the parked
        # path, the deepest arrival reorder observed, and how many
        # duplicate answers the dedupe refused (the committed
        # ROBUSTNESS artifact's 0-double-apply bound reads this)
        self.oracle = {
            "answers_parked": 0,     # per-slot answers accepted into a park
            "abstentions": 0,        # abstain verbs (slot left open)
            "poisoned": 0,           # answers corrupted by oracle_poison
            "deferred_rounds_completed": 0,  # rounds dispatched via parking
            "reorder_depth_max": 0,  # deepest out-of-order arrival seen
            "double_apply_rejects": 0,  # duplicate answers refused
        }
        # surrogate-scorer evidence provider (--eig-scorer surrogate:k):
        # set by the app to a () -> dict callback summing the slab-carried
        # fit counters over its buckets, so /stats and /metrics read
        # CURRENT counters on demand without a per-tick device sync. The
        # returned keys (surrogate_rounds, surrogate_fallbacks,
        # surrogate_fit_refreshes, surrogate_contract_margin) merge into
        # the snapshot; {} when no surrogate bucket exists.
        self.surrogate_provider = None
        # cross-session prior evidence provider (--surrogate-prior pool):
        # set by the app to a () -> dict callback merging the pool's
        # contribution counters with the slab-read warmup-credit/
        # gate-rejection counters (prior_sessions_contributed,
        # prior_warmup_rounds_skipped, prior_gate_rejections). None when
        # the prior is off — the families are then ABSENT from /stats
        # and /metrics, not zero, exactly like the surrogate's.
        self.prior_provider = None
        # cold-tier spill store stats provider (serve/tiering.py): a
        # () -> dict of the v3 store's segment/index/compaction gauges,
        # surfaced under snapshot["spill"]. None when no spill dir.
        self.spill_provider = None
        # decision-quality plane provider (telemetry/quality.py): a
        # () -> dict of calibration / drift / shadow-audit evidence,
        # surfaced under snapshot["quality"]. None when --no-quality —
        # the families are then ABSENT, not zero (spill's contract).
        self.quality_provider = None
        # OpenMetrics exemplars: per-ring, the most recent TRACED sample
        # whose latency cleared the ring's p99 (gate lazily refreshed from
        # the percentile reduction each snapshot — the record path stays a
        # compare + tuple store, no reduction). A slow request on /metrics
        # is then one hop from its stitched trace.
        self._exemplars: dict[str, tuple] = {}   # ring -> (seconds, trace_id)
        self._exemplar_gate: dict[str, float] = {}  # ring -> p99 seconds

    def _note_exemplar(self, ring: str, seconds: float,
                       trace_id) -> None:
        """Keep (seconds, trace_id) if it clears the ring's last-known p99
        (or no gate exists yet). Caller holds the lock."""
        if not trace_id:
            return
        gate = self._exemplar_gate.get(ring)
        if gate is None or seconds >= gate:
            self._exemplars[ring] = (float(seconds), str(trace_id))

    # -- recording (request path: O(1), no reductions) ---------------------
    def record_dispatch(self, n_requests: int, queue_depth: int,
                        seconds: float, step_seconds: float = None,
                        warm: bool = None) -> None:
        with self._lock:
            self.dispatches += 1
            self.requests += n_requests
            self.max_occupancy = max(self.max_occupancy, n_requests)
            self._occupancy.append(n_requests)
            self._queue_depth.append(queue_depth)
            self._dispatch_s.append(seconds)
            if step_seconds is not None:
                self._step_s.append(step_seconds)
            if warm is not None:
                if warm:
                    self.warm_hits += 1
                else:
                    self.warm_misses += 1

    def record_request_latency(self, seconds: float,
                               trace_id=None) -> None:
        with self._lock:
            self._request_s.append(seconds)
            self._note_exemplar("request_latency", seconds, trace_id)

    def record_queue_wait(self, seconds: float, trace_id=None) -> None:
        with self._lock:
            self._queue_wait_s.append(seconds)
            self._note_exemplar("queue_wait", seconds, trace_id)

    def record_warm_pool(self, size: int, seconds: float) -> None:
        """One warm-up pass finished: pool size + wall time it took."""
        with self._lock:
            self.warm_pool_size = int(size)
            self.warm_pool_seconds = float(seconds)

    def record_tier(self, event: str, src: str = None,
                    seconds: float = None, via: str = None) -> None:
        """One tiering event: ``demote`` | ``hibernate`` | ``wake`` (with
        its source tier, wall seconds, and restore path) | ``wake_failed``."""
        with self._lock:
            if event == "demote":
                self.demotions += 1
            elif event == "hibernate":
                self.hibernates += 1
            elif event == "peer_page":
                self.peer_pages += 1
            elif event == "wake":
                self.wakes += 1
                if src == "warm":
                    self.wakes_from_warm += 1
                elif src == "cold":
                    self.wakes_from_cold += 1
                if via == "replay":
                    self.wakes_via_replay += 1
                if seconds is not None:
                    self._wake_s.append(seconds)
            elif event == "wake_failed":
                self.wake_failures += 1
            else:
                raise ValueError(f"unknown tier event {event!r}")

    def set_tier_occupancy(self, hot: int, warm: int, cold: int) -> None:
        with self._lock:
            self.tier_occupancy = {"hot": int(hot), "warm": int(warm),
                                   "cold": int(cold)}

    def record_recovery(self, event: str) -> None:
        """One fault-tolerance event (see the ``recovery`` counter keys)."""
        with self._lock:
            if event not in self.recovery:
                raise ValueError(f"unknown recovery event {event!r}")
            self.recovery[event] += 1

    def record_oracle(self, event: str, depth: int = None) -> None:
        """One crowd-oracle answer event: ``parked`` | ``abstain`` |
        ``poisoned`` | ``round_completed`` | ``double_apply_reject``;
        ``depth`` updates the reorder-depth high-water mark."""
        with self._lock:
            if event == "parked":
                self.oracle["answers_parked"] += 1
            elif event == "abstain":
                self.oracle["abstentions"] += 1
            elif event == "poisoned":
                self.oracle["poisoned"] += 1
            elif event == "round_completed":
                self.oracle["deferred_rounds_completed"] += 1
            elif event == "double_apply_reject":
                self.oracle["double_apply_rejects"] += 1
            else:
                raise ValueError(f"unknown oracle event {event!r}")
            if depth is not None:
                self.oracle["reorder_depth_max"] = max(
                    self.oracle["reorder_depth_max"], int(depth))

    def record_fencing_rejection(self) -> None:
        """One stale-epoch verb refused (the ownership fence held)."""
        with self._lock:
            self.fencing_rejections += 1

    def record_session(self, event: str) -> None:
        with self._lock:
            if event == "open":
                self.sessions_opened += 1
            elif event == "close":
                self.sessions_closed += 1
            elif event == "reject":
                self.sessions_rejected += 1
            elif event == "request_reject":
                self.requests_rejected += 1
            else:
                raise ValueError(f"unknown session event {event!r}")

    # -- reading -----------------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-able dict: counters + reduced percentiles (the /stats
        payload and the loadgen report's server-side half)."""
        with self._lock:
            occ = list(self._occupancy)
            depth = list(self._queue_depth)
            snap = {
                "uptime_s": time.monotonic() - self.started,
                "dispatches": self.dispatches,
                "requests": self.requests,
                "sessions_opened": self.sessions_opened,
                "sessions_closed": self.sessions_closed,
                "sessions_rejected": self.sessions_rejected,
                "requests_rejected": self.requests_rejected,
                "fencing_rejections": self.fencing_rejections,
                "max_occupancy": self.max_occupancy,
                "mean_occupancy": (float(np.mean(occ)) if occ else None),
                "mean_queue_depth": (float(np.mean(depth)) if depth
                                     else None),
                "dispatch_latency": _percentiles(self._dispatch_s),
                "request_latency": _percentiles(self._request_s),
                # the p99 attribution triplet: where a request's wall time
                # went — queued behind a tick, host-side dispatch fan-out,
                # or the compiled slab step itself
                "queue_wait": _percentiles(self._queue_wait_s),
                "step_latency": _percentiles(self._step_s),
                "warm_pool": {
                    "size": self.warm_pool_size,
                    "warm_s": self.warm_pool_seconds,
                    "hits": self.warm_hits,
                    "misses": self.warm_misses,
                },
                "recovery": dict(self.recovery),
                "oracle": dict(self.oracle),
                # tiered-state evidence: occupancy per tier, paging
                # counters, and the wake-latency ring percentiles
                "tiers": dict(self.tier_occupancy),
                "demotions": self.demotions,
                "hibernates": self.hibernates,
                "peer_pages": self.peer_pages,
                "wakes": self.wakes,
                "wakes_from_warm": self.wakes_from_warm,
                "wakes_from_cold": self.wakes_from_cold,
                "wakes_via_replay": self.wakes_via_replay,
                "wake_failures": self.wake_failures,
                "wake_latency": _percentiles(self._wake_s),
                # ring fill: how much recent-window evidence backs the
                # percentiles above (fill == capacity -> the ring has
                # wrapped and older events have been evicted)
                # traced p99 outliers per latency ring (OpenMetrics
                # exemplar source; absent ring -> no traced outlier yet)
                "exemplars": {
                    ring: {"value_s": v, "trace_id": tid}
                    for ring, (v, tid) in self._exemplars.items()
                },
                "ring_capacity": _RING,
                "ring_fill": {
                    "occupancy": len(self._occupancy),
                    "queue_depth": len(self._queue_depth),
                    "dispatch_latency": len(self._dispatch_s),
                    "request_latency": len(self._request_s),
                    "queue_wait": len(self._queue_wait_s),
                    "step_latency": len(self._step_s),
                    "wake_latency": len(self._wake_s),
                },
            }
            # refresh the exemplar gates from the reduction just paid: the
            # NEXT traced samples are compared against the current p99
            for ring in ("request_latency", "queue_wait"):
                p99 = snap[ring]["p99_ms"]
                if p99 is not None:
                    self._exemplar_gate[ring] = p99 / 1e3
        # outside the lock: the provider takes bucket dispatch locks of
        # its own, and a lock inversion against record_dispatch (batcher
        # thread holding a bucket lock while recording) must be impossible
        provider = self.surrogate_provider
        if provider is not None:
            try:
                snap.update(provider() or {})
            except Exception:
                pass  # stats must never fail on a mid-teardown bucket
        provider = self.prior_provider
        if provider is not None:
            try:
                snap.update(provider() or {})
            except Exception:
                pass
        provider = self.spill_provider
        if provider is not None:
            try:
                spill = provider()
                if spill:
                    snap["spill"] = spill
            except Exception:
                pass
        provider = self.quality_provider
        if provider is not None:
            try:
                quality = provider()
                if quality:
                    snap["quality"] = quality
            except Exception:
                pass
        return snap

    def log_to_store(self, store, experiment: str = "serve",
                     run_name: str | None = None, params: dict | None = None):
        """Flush a snapshot into the tracking store (one run, flat metrics).

        Uses the same experiment -> run layout the benchmark CLI writes, so
        serving runs sit next to experiment runs in one sqlite DB and the
        analysis SQL can join them. Returns the run_uuid."""
        snap = self.snapshot()
        name = run_name or f"{experiment}-metrics"
        with store.run(experiment, name, params=params or {}) as run:
            for key, val in snap.items():
                if isinstance(val, dict):
                    for sub, v in val.items():
                        if isinstance(v, (int, float)):
                            run.log_metric(f"{key}.{sub}", float(v))
                elif isinstance(val, (int, float)):
                    run.log_metric(key, float(val))
        return run.run_uuid
