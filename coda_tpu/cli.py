"""Benchmark CLI: ``python main.py --task T --method M ...``.

Argument-compatible with the reference experiment driver (reference
``main.py:28-53``): same task/method/seed/loss/CODA hyperparameter flags, the
same regret/cumulative-regret metrics per labeling round, and the same
experiment -> parent-run -> seed-child-run tracking layout.

TPU-native execution model: instead of a Python loop calling the selector
per round per seed, every seed's full 100-round experiment is one compiled
``lax.scan`` and all seeds run batched under ``vmap`` in a single device
program (reference: one host loop per seed, ``main.py:89-103``). Metrics
stream to the tracking store *after* the compiled run, in one batch per seed.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
import time

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="TPU-native active model selection")
    # dataset settings
    p.add_argument("--task", default=None, help="task name, e.g. cifar10_5592")
    p.add_argument("--data-dir", default="data")
    p.add_argument(
        "--synthetic", default=None, metavar="H,N,C",
        help="run on a seeded synthetic task of this shape instead of files",
    )

    # benchmarking settings
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--seeds", type=int, default=5)
    p.add_argument("--force-rerun", action="store_true",
                   help="Overwrite existing finished runs.")
    p.add_argument("--experiment-name", default=None)
    p.add_argument("--no-mlflow", action="store_true",
                   help="Disable tracking-store logging.")
    p.add_argument("--tracking-db", default="coda.sqlite",
                   help="Path of the sqlite tracking database.")

    # general method settings
    p.add_argument("--loss", default="acc", help="{acc, ce}")
    p.add_argument("--method", default="iid",
                   help="{iid, uncertainty, coda*, activetesting, vma, model_picker}")
    def _acq_batch(v):
        q = int(v)
        if q < 1:
            # a clamped-downstream value would be fingerprinted as a knob
            # that never ran, turning bitwise-identical replays into a
            # fake knob diff
            raise argparse.ArgumentTypeError(
                f"acq-batch must be >= 1, got {q}")
        return q

    p.add_argument("--acq-batch", type=_acq_batch, default=1, metavar="Q",
                   help="oracle labels acquired per round (default 1 = "
                        "the paper's protocol, bitwise-unchanged). Q > 1 "
                        "selects Q points per round in ONE scoring pass — "
                        "CODA: greedy EIG with an information-overlap "
                        "penalty off the cached hypothetical posteriors; "
                        "other methods: argmin/argmax top-Q or sequential "
                        "draws — and applies all Q answers as one fused "
                        "multi-row update, so wall-clock-to-target-regret "
                        "drops ~Qx when oracles answer in parallel "
                        "(--iters then counts ROUNDS: Q*iters labels)")

    # CODA settings (same flags/defaults as the reference)
    p.add_argument("--alpha", default=0.9, type=float)
    p.add_argument("--learning-rate", default=0.01, type=float)
    p.add_argument("--multiplier", default=2.0, type=float)
    p.add_argument("--prefilter-n", type=int, default=0,
                   help="Randomly subsample n candidates per iteration.")
    p.add_argument("--no-diag-prior", action="store_true",
                   help="Disable diagonal prior (ablation 1).")
    p.add_argument("--q", default="eig",
                   help="Acquisition function {eig, iid, uncertainty} (ablation 2).")

    # ModelPicker settings
    def _epsilon(v):
        f = float(v)
        if not 0.0 < f < 1.0:
            raise argparse.ArgumentTypeError(
                f"epsilon must be in (0, 1), got {f}")
        return f

    p.add_argument("--epsilon", type=_epsilon, default=None,
                   help="ModelPicker epsilon in (0, 1); default: the "
                        "per-task tuned TASK_EPS table "
                        "(reference modelpicker.py:5-35)")

    # TPU execution settings (no reference equivalent)
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable intra-run checkpoint/resume under this dir "
                        "(seeds run serially, resuming from the last chunk)")
    p.add_argument("--checkpoint-every", type=int, default=25,
                   help="rounds between checkpoints (with --checkpoint-dir)")
    p.add_argument("--eig-chunk", type=int, default=1024,
                   help="lax.map batch size for the EIG scoring pass.")
    p.add_argument("--eig-mode", default="auto",
                   choices=["auto", "incremental", "factored", "rowscan",
                            "direct"],
                   help="EIG kernel: auto picks incremental (cached "
                        "per-class P(best), C-fold fewer FLOPs/round) when "
                        "its cache fits, else factored, else rowscan")
    p.add_argument("--eig-backend", default="auto",
                   choices=["auto", "jnp", "pallas"],
                   help="incremental-EIG scoring backend: pallas = fused "
                        "single-HBM-pass TPU kernel (interpreted off-TPU); "
                        "auto (default) = pallas on a single-chip TPU "
                        "process, jnp elsewhere")
    p.add_argument("--eig-precision", default="highest",
                   choices=["highest", "high", "default"],
                   help="matmul precision of the EIG table einsums: highest "
                        "= reference numerics (parity-tested default); "
                        "lower tiers trade trace parity for MXU throughput")
    p.add_argument("--eig-cache-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="storage dtype of the incremental P(best) cache: "
                        "bfloat16 halves the scoring pass's HBM stream "
                        "(opt-in numerics, like --eig-precision)")
    p.add_argument("--eig-refresh", default="precomputed",
                   choices=["precomputed", "fused"],
                   help="where the incremental row-refresh einsums run: "
                        "precomputed = XLA-HIGHEST (reference numerics); "
                        "fused = inside the pallas scoring kernel (fp32 "
                        "MXU dots overlap the cache read — opt-in "
                        "numerics, pallas backend only)")
    p.add_argument("--eig-entropy", default="exact",
                   choices=["exact", "approx"],
                   help="log lowering of the expected-entropy scoring "
                        "chain: exact = transcendental log2 (reference "
                        "numerics, the parity-tested default); approx = "
                        "bit-extracted exponent + degree-6 mantissa "
                        "polynomial on the clamped [1e-12, 1] domain "
                        "(max |Dscore| <= 1e-4 — cuts the N*C*H "
                        "transcendental tail that caps the bf16 "
                        "headline; opt-in numerics like --eig-precision)")
    p.add_argument("--posterior", default="dense", metavar="dense|sparse:K",
                   help="Dirichlet posterior representation: dense = the "
                        "reference (H, C, C) tensor; sparse:K keeps each "
                        "class row as diagonal + top-K off-diagonal "
                        "entries + one residual mass (~(2K+2)/C of the "
                        "dense state; label updates touch one row with a "
                        "sparse scatter, the per-round Beta extraction "
                        "reads O(H*K) not O(H*C^2)) — the large-C rung of "
                        "the numerics ladder (incremental tier only; "
                        "sparse:K>=C is bitwise-equal to dense, K<C holds "
                        "the documented 2.34e-4 score contract)")
    p.add_argument("--eig-pbest", default="quad",
                   choices=["quad", "amortized"],
                   help="hypothetical P(best) row-refresh integral: quad "
                        "= the reference G-point Beta quadrature; "
                        "amortized = closed-form logistic-normal (Laplace "
                        "bridge, arXiv 1905.12194) tables, engaged per "
                        "round only where the labeled row's concentration "
                        "provably holds the 2.34e-4 score contract "
                        "(below the committed gate the quadrature runs "
                        "unchanged; opt-in numerics like --eig-entropy)")
    p.add_argument("--eig-scorer", default="exact",
                   metavar="exact|surrogate:k",
                   help="who scores the round: exact (default, the full "
                        "O(N*C*H) chain, bitwise-pinned) or surrogate:k "
                        "— a carried closed-form ridge over ~16 cheap "
                        "per-candidate features scores ALL N points, the "
                        "exact chain refreshes only its top-k shortlist "
                        "+ a rotating audit set, and a structural trust "
                        "gate (rank agreement + the committed 2.34e-4 "
                        "score contract, measured every round on the "
                        "exactly-scored rows) falls back to a full exact "
                        "pass when violated; warmup rounds are always "
                        "exact (incremental tier only; surrogate:k>=N is "
                        "bitwise-equal to exact)")
    p.add_argument("--surrogate-prior", default="off",
                   choices=["off", "pool"],
                   help="surrogate scorer only: 'pool' seeds the carried "
                        "ridge fit from a cross-session prior (the serve "
                        "pool's statistics — see serve/priors.py) instead "
                        "of zeros, granting warmup credit; the per-round "
                        "trust gate is unchanged. 'off' (default) is "
                        "bitwise-identical to the pre-pool scorer")
    p.add_argument("--oracle-noise", default=None, metavar="SPEC",
                   help="crowd-oracle spec: omitted/'clean' = the plain "
                        "perfect oracle (bitwise-pinned program); else "
                        "comma k=v pairs, e.g. 'annotators=8,votes=3,"
                        "acc=0.55:0.95,abstain=0.1,adversarial=1,trust=32,"
                        "reliability=learned,seed=0' — per-annotator "
                        "confusion noise, abstention, poisoned annotators, "
                        "with a jointly-learned Dawid-Skene reliability "
                        "posterior weighting every label update "
                        "(ARCHITECTURE.md 'Oracles')")
    p.add_argument("--oracle-annotators", type=int, default=None,
                   metavar="A",
                   help="override the crowd pool size of --oracle-noise "
                        "(sweep convenience; ignored when clean)")
    p.add_argument("--oracle-reliability", default=None,
                   choices=["learned", "majority"],
                   help="override the aggregation mode of --oracle-noise: "
                        "learned = trust-gated Dawid-Skene posterior "
                        "weights (default), majority = plain majority "
                        "vote (the ablation arm)")
    p.add_argument("--pi-update", default="auto",
                   choices=["auto", "delta", "exact"],
                   help="incremental pi-hat refresh: auto (default) = exact "
                        "on TPU / delta elsewhere; delta = bandwidth-lean "
                        "exact increment; exact = strict reference float "
                        "choreography")
    p.add_argument("--mesh", default=None, metavar="AXIS=K,...",
                   help="shard the (H,N,C) tensor, e.g. 'data=4' or 'data=4,model=2'")
    p.add_argument("--suite-devices", default=None, metavar="auto|N",
                   help="suite runs only (`cli suite`, run_suite, "
                        "bench_suite): place independent task-method "
                        "dispatches on this many local devices via the "
                        "task-parallel scheduler ('auto' = all); default "
                        "= serial dispatch on one device")
    p.add_argument("--schedule", default="lpt", choices=["lpt", "fifo"],
                   help="with --suite-devices: dispatch order — lpt = "
                        "longest-processing-time-first from the "
                        "per-family warm cost profile (default), fifo = "
                        "caller order")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (cpu/tpu), e.g. for local runs")
    p.add_argument("--compilation-cache-dir", default=None,
                   help="persistent jax compilation cache: executables "
                        "serialize here and later processes deserialize "
                        "instead of recompiling (cache-deserialized "
                        "executables measured 3.4x faster to obtain than "
                        "fresh compiles, NOTES_r08) — the cold-start lever "
                        "the serve warm pool builds on")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler device trace of the compiled "
                        "run into this directory (TensorBoard/Perfetto)")
    p.add_argument("--telemetry-dir", default=None,
                   help="write structured telemetry artifacts there: "
                        "trace.json (Perfetto host/device spans), "
                        "telemetry.json (jit recompiles, HBM watermarks), "
                        "metrics.prom (Prometheus text); scalars also land "
                        "in the tracking store unless --no-mlflow")
    p.add_argument("--record-dir", default=None,
                   help="decision flight recorder: write a per-round "
                        "provenance record (chosen idx, oracle label, "
                        "top-k EIG scores, runner-up gap, P(best) digest, "
                        "PRNG key counters) + environment fingerprint "
                        "there; verify later with "
                        "`python -m coda_tpu.cli replay <dir>`")
    p.add_argument("--record-topk", type=int, default=8,
                   help="how many top-scored candidates the flight "
                        "recorder captures per round (with --record-dir)")
    p.add_argument("--no-cost-capture", action="store_true",
                   help="skip per-executable XLA cost attribution "
                        "(telemetry/costs.py): the engine entry then "
                        "compiles through the plain jit path and "
                        "telemetry.json carries no 'costs' section")
    p.add_argument("--debug-viz", action="store_true",
                   help="log P(best) / regret-curve charts as artifacts to "
                        "the tracking store (reference _DEBUG_VIZ analog)")
    return p.parse_args(argv)


def load_dataset(args):
    """Load the dataset an argparse namespace points at.

    Only ``task`` / ``data_dir`` / ``synthetic`` / ``mesh`` are read, with
    getattr defaults so partial namespaces (e.g. the demo's parser) work.
    """
    from coda_tpu.data import Dataset, make_synthetic_task

    args = argparse.Namespace(
        task=getattr(args, "task", None),
        data_dir=getattr(args, "data_dir", "data"),
        synthetic=getattr(args, "synthetic", None),
        mesh=getattr(args, "mesh", None),
    )
    if args.synthetic:
        H, N, C = (int(x) for x in args.synthetic.split(","))
        return make_synthetic_task(seed=0, H=H, N=N, C=C,
                                   name=args.task or f"synthetic_{H}x{N}x{C}")
    if args.task is None:
        raise SystemExit("--task or --synthetic is required")
    from coda_tpu.data import find_task_file

    fp = find_task_file(args.data_dir, args.task)
    if fp is None:
        raise SystemExit(
            f"No data file for task '{args.task}' under {args.data_dir}/")
    sharding = None
    if args.mesh:
        from coda_tpu.parallel import mesh_from_spec, preds_sharding

        sharding = preds_sharding(mesh_from_spec(args.mesh))
    return Dataset.from_file(fp, sharding=sharding, name=args.task)


def build_selector_factory(args, task_name: str):
    """``preds -> Selector`` for the configured method.

    Returned as a factory (not a built selector) so callers can construct the
    selector *inside* a jitted function, keeping the prediction tensor a
    traced argument instead of a captured constant
    (see ``run_seeds_compiled``).
    """
    from coda_tpu.selectors import (
        CODAHyperparams,
        SELECTOR_FACTORIES,
        TASK_EPS,
        make_coda,
        make_modelpicker,
    )
    from coda_tpu.losses import LOSS_FNS

    loss_fn = LOSS_FNS[args.loss]
    method = args.method
    if method.startswith("coda"):
        hp = CODAHyperparams(
            prefilter_n=args.prefilter_n,
            alpha=args.alpha,
            learning_rate=args.learning_rate,
            multiplier=args.multiplier,
            disable_diag_prior=args.no_diag_prior,
            q=args.q,
            eig_chunk=args.eig_chunk,
            eig_mode=getattr(args, "eig_mode", "auto"),
            eig_backend=getattr(args, "eig_backend", "auto"),
            eig_precision=getattr(args, "eig_precision", "highest"),
            eig_cache_dtype=getattr(args, "eig_cache_dtype", "float32"),
            eig_refresh=getattr(args, "eig_refresh", "precomputed"),
            eig_entropy=getattr(args, "eig_entropy", "exact"),
            posterior=getattr(args, "posterior", "dense"),
            eig_pbest=getattr(args, "eig_pbest", "quad"),
            eig_scorer=getattr(args, "eig_scorer", "exact"),
            surrogate_prior=getattr(args, "surrogate_prior", "off"),
            pi_update=getattr(args, "pi_update", "auto"),
            # a --mesh run declares its sharding so the pallas fast path
            # can shard_map the kernels over the data axis (make_coda
            # rejects specs the path can't support when pallas is explicit;
            # 'auto' demotes to jnp on them)
            shard_spec=getattr(args, "mesh", None) or "",
            # vmapped seeds each carry their own incremental cache; the
            # auto eig_mode budget must see the whole batch. Runners with a
            # different execution width (the suite's dedup batches, future
            # serial runners) set args.n_parallel explicitly; the default
            # infers it from the CLI's all-seeds vmap (the serial
            # checkpoint path runs one seed at a time).
            n_parallel=(getattr(args, "n_parallel", None)
                        or (1 if getattr(args, "checkpoint_dir", None)
                            else max(1, getattr(args, "seeds", 1)))),
        )
        return lambda preds: make_coda(preds, hp, name=method)
    if method == "model_picker":
        eps = getattr(args, "epsilon", None)
        if eps is None:
            eps = TASK_EPS.get(task_name)
        if eps is None:
            print(f"{task_name} not in TASK_EPS; using default")
            return lambda preds: make_modelpicker(preds)
        return lambda preds: make_modelpicker(preds, epsilon=eps)
    if method in ("activetesting", "vma"):
        return lambda preds: SELECTOR_FACTORIES[method](
            preds, loss_fn=loss_fn, budget=args.iters)
    if method in SELECTOR_FACTORIES:
        return lambda preds: SELECTOR_FACTORIES[method](preds, loss_fn=loss_fn)
    raise SystemExit(f"{method} is not a supported method.")


def build_selector(args, dataset):
    return build_selector_factory(args, dataset.name)(dataset.preds)


def _log_debug_viz(run, selector, result, seed: int, iters: int) -> None:
    """Log end-of-run charts as artifacts (reference ``_DEBUG_VIZ`` analog,
    ``coda/coda.py:299-303,337-341`` — which logs per-step bar charts; here
    the per-step traces come out of the scan and the final P(best) is
    recovered by replaying the recorded label sequence through the pure
    ``update`` function, so nothing slows the compiled hot loop)."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.utils.viz import plot_bar, plot_series

    regret = np.asarray(result.regret)[seed]
    cum = np.asarray(result.cumulative_regret)[seed]
    run.log_figure(
        "regret_curve",
        plot_series([regret, cum], title=f"seed {seed}",
                    ylabel="regret", labels=["regret", "cumulative"]),
    )
    get_pbest = selector.extras.get("get_pbest")
    if get_pbest is None:
        return
    idxs = np.asarray(result.chosen_idx)[seed]
    tcs = np.asarray(result.true_class)[seed]
    state = jax.jit(selector.init)(jax.random.PRNGKey(seed))
    update = jax.jit(selector.update)
    for i in range(iters):
        state = update(state, jnp.asarray(int(idxs[i])),
                       jnp.asarray(int(tcs[i])), jnp.asarray(0.0))
    pbest = np.asarray(jax.jit(get_pbest)(state))
    run.log_figure(
        "pbest",
        plot_bar(pbest, title=f"P(best) after {iters} labels (seed {seed})",
                 highlight=int(pbest.argmax()), xlabel="model",
                 ylabel="P(best)"),
    )


def trace_main(argv=None):
    """``cli trace <trace_id> --url http://host:port --out trace.json``.

    Hits the serve front door's ``GET /trace/id/{trace_id}``. Against a
    router that is the cross-process stitched Chrome file (one Perfetto
    process lane per replica); against a single replica it is that
    replica's own spans for the trace, wrapped for the same viewer."""
    import json as _json
    import urllib.request

    p = argparse.ArgumentParser(
        prog="coda_tpu.cli trace",
        description="fetch one distributed trace as Chrome/Perfetto JSON")
    p.add_argument("trace_id", help="32-hex trace id (from an exemplar, a "
                   "recorder row, or loadgen --trace-sample output)")
    p.add_argument("--url", default="http://127.0.0.1:8000",
                   help="serve front door (router or replica) base URL")
    p.add_argument("--out", default="trace.json",
                   help="output path for the Chrome trace_event JSON")
    args = p.parse_args(argv)

    url = args.url.rstrip("/") + f"/trace/id/{args.trace_id}"
    with urllib.request.urlopen(url, timeout=30.0) as resp:
        payload = _json.loads(resp.read().decode("utf-8"))
    if "traceEvents" not in payload:
        # a bare replica returns its trace_payload wire form; wrap it so
        # the output is always Perfetto-loadable
        from coda_tpu.telemetry.spans import stitch_traces

        payload = stitch_traces(
            [dict(payload, process=payload.get("process") or "replica")])
    n = len([e for e in payload.get("traceEvents", ())
             if e.get("ph") == "X"])
    procs = payload.get("processes")
    with open(args.out, "w") as f:
        _json.dump(payload, f)
    print(f"trace {args.trace_id}: {n} span(s)"
          + (f" across {procs}" if procs else "")
          + f" -> {args.out}")
    if n == 0:
        print("warning: no spans retained for this trace "
              "(evicted, unsampled, or tracing disabled)")
        return 1
    return 0


def _quality_report(card: dict) -> list:
    """Human lines for one decision-quality scorecard (replica shape —
    the per-replica half of a router card goes through this too)."""
    lines = []
    verdict = card.get("verdict") or {}
    if verdict:
        worst = verdict.get("worst_ece")
        lines.append("verdict: calibration=%s%s  audit=%s  drift=%s" % (
            verdict.get("calibration"),
            f" (worst ECE {worst:.4f})" if worst is not None else "",
            verdict.get("audit"), verdict.get("drift")))
    for task, cal in sorted((card.get("calibration") or {}).items()):
        ece, brier = cal.get("ece"), cal.get("brier")
        lines.append(
            f"  calibration[{task}]: n={cal.get('n')}"
            + (f" ece={ece:.4f}" if ece is not None else " ece=-")
            + (f" brier={brier:.4f}" if brier is not None else ""))
    audit = card.get("audit") or {}
    if audit:
        lines.append(
            "  audit: %d replayed (%d rounds), %d skipped, "
            "%d divergence(s) (%d recent), %d tampered" % (
                audit.get("audits_total", 0),
                audit.get("rounds_verified", 0),
                audit.get("audits_skipped", 0),
                audit.get("divergences_total", 0),
                audit.get("divergences_recent", 0),
                audit.get("tampered_total", 0)))
        gap = audit.get("prior_gap")
        if gap is not None:
            lines.append(f"  audit: seeded-vs-cold prior gap "
                         f"{gap:.3f} over "
                         f"{audit.get('prior_gap_sessions')} session(s)")
        for d in audit.get("last_divergences") or ():
            lines.append(f"    diverged: session {d.get('session')} "
                         f"round {d.get('round')}: {d.get('detail')}")
    for name, det in sorted((card.get("drift") or {}).items()):
        lines.append(
            "  drift[%s]: %s stat=%.4f fired=%d cleared=%d obs=%d" % (
                name, "FIRING" if det.get("firing") else "ok",
                det.get("statistic") or 0.0, det.get("fired_total", 0),
                det.get("cleared_total", 0), det.get("observations", 0)))
    return lines


def quality_main(argv=None):
    """``cli quality --url http://host:port``: the decision-quality
    report. Hits ``GET /fleet/quality`` (router: per-replica scorecards
    + fleet verdict; replica: its own plane), falls back to the
    ``quality`` section of ``/stats``; exits 1 when any organ grades
    diverged / miscalibrated / firing."""
    import json as _json
    import urllib.error
    import urllib.request

    p = argparse.ArgumentParser(
        prog="coda_tpu.cli quality",
        description="decision-quality scorecard: live calibration, drift "
                    "detectors, shadow-audit divergences")
    p.add_argument("--url", default="http://127.0.0.1:8000",
                   help="serve front door (router or replica) base URL")
    p.add_argument("--json", action="store_true",
                   help="print the raw scorecard JSON instead of the "
                        "report")
    args = p.parse_args(argv)

    base = args.url.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/fleet/quality",
                                    timeout=30.0) as resp:
            card = _json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise
        # --no-quality replica, or a pre-r20 server: try /stats
        with urllib.request.urlopen(base + "/stats", timeout=30.0) as resp:
            stats = _json.loads(resp.read().decode("utf-8"))
        card = stats.get("quality")
        if card is None:
            print("quality plane disabled on this server (--no-quality)")
            return 1
    if args.json:
        print(_json.dumps(card, indent=2, sort_keys=True))
    lines = []
    if card.get("role") == "router":
        verdict = card.get("verdict") or {}
        worst = verdict.get("worst_ece")
        lines.append("fleet verdict: calibration=%s%s  audit=%s  "
                     "drift=%s" % (
                         verdict.get("calibration"),
                         f" (worst ECE {worst:.4f})"
                         if worst is not None else "",
                         verdict.get("audit"), verdict.get("drift")))
        for rid, rep in sorted((card.get("replicas") or {}).items()):
            if rep.get("error"):
                lines.append(f"replica {rid}: ERROR {rep['error']}")
            elif rep.get("enabled") is False:
                lines.append(f"replica {rid}: quality plane disabled")
            else:
                lines.append(f"replica {rid}:")
                lines.extend(_quality_report(rep))
        bad = verdict
    else:
        lines.extend(_quality_report(card))
        bad = card.get("verdict") or {}
    if not args.json:
        print("\n".join(lines) if lines else "no quality evidence yet")
    ok = (bad.get("audit") != "diverged"
          and bad.get("calibration") != "miscalibrated"
          and bad.get("drift") != "firing")
    return 0 if ok else 1


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        # `python -m coda_tpu.cli serve ...`: the batched multi-session
        # serving layer (many interactive sessions, one compiled step per
        # dispatch) instead of a batch experiment run
        from coda_tpu.serve.server import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "replay":
        # `python -m coda_tpu.cli replay <record-dir> ...`: re-execute a
        # flight-recorder record and triage any divergence (first diverging
        # round + quantity); `--against` diffs two records instead
        from coda_tpu.engine.replay import replay_main

        return replay_main(argv[1:])
    if argv and argv[0] == "replay-serve":
        # `python -m coda_tpu.cli replay-serve <record-dir> ...`: verify
        # serving-session JSONL streams (a serve --record-dir) by bitwise
        # replay through a fresh slab — the interactive-session twin of
        # `replay`, and the offline face of crash restore
        from coda_tpu.serve.recovery import replay_serve_main

        return replay_serve_main(argv[1:])
    if argv and argv[0] == "trace":
        # `python -m coda_tpu.cli trace <trace_id> --url http://router`:
        # fetch one distributed trace, stitched across every replica's
        # process lane, and write a Perfetto-loadable trace.json
        return trace_main(argv[1:])
    if argv and argv[0] == "quality":
        # `python -m coda_tpu.cli quality --url http://router`: the
        # decision-quality scorecard (calibration / drift / shadow audit)
        # as a human report; exit 1 when any organ grades unhealthy
        return quality_main(argv[1:])
    if argv and argv[0] == "suite":
        # `python -m coda_tpu.cli suite ...`: the in-process sweep driver
        # (scripts/run_suite.py) — grows --task-batch/--suite-devices/
        # --schedule for multi-device task-parallel execution
        import importlib.util

        fp = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                          "scripts", "run_suite.py")
        if not os.path.exists(fp):
            raise SystemExit(
                "cli suite needs scripts/run_suite.py (repo checkout); "
                "run it directly from an installed package instead")
        spec = importlib.util.spec_from_file_location("run_suite", fp)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main(argv[1:])
    args = parse_args(argv)
    if args.suite_devices is not None:
        raise SystemExit(
            "--suite-devices/--schedule configure suite sweeps, which the "
            "single-task runner never dispatches; use "
            "`python -m coda_tpu.cli suite ...` (or scripts/run_suite.py / "
            "scripts/bench_suite.py)")
    from coda_tpu.utils.platform import enable_compilation_cache, pin_platform

    pin_platform(args.platform)
    enable_compilation_cache(args.compilation_cache_dir)
    if args.no_cost_capture:
        from coda_tpu.telemetry import costs as _costs

        _costs.set_enabled(False)

    import jax

    from coda_tpu.losses import LOSS_FNS
    from coda_tpu.oracle import true_losses

    # telemetry before any compile, so the jax.monitoring recompile hook
    # sees every backend compile this run pays
    telemetry = None
    if args.telemetry_dir:
        from coda_tpu.telemetry import Telemetry

        telemetry = Telemetry(out_dir=args.telemetry_dir)

    def tele_span(name, **attrs):
        return (telemetry.span(name, lane="host:main", annotate=True,
                               **attrs)
                if telemetry is not None else contextlib.nullcontext())

    print("devices:", jax.devices())
    with tele_span("load_dataset"):
        dataset = load_dataset(args)
    H, N, C = dataset.shape
    print(f"Loaded preds of shape ({H}, {N}, {C})")
    if dataset.labels is None:
        raise SystemExit("Oracle needs labels!")

    loss_fn = LOSS_FNS[args.loss]
    model_losses = true_losses(dataset.preds, dataset.labels, loss_fn)
    best_loss = float(np.asarray(model_losses).min())
    print("Best possible loss is", best_loss)

    factory = build_selector_factory(args, dataset.name)
    selector = factory(dataset.preds)

    from coda_tpu.utils.profiling import trace as profiler_trace

    t0 = time.perf_counter()
    with profiler_trace(args.profile_dir):
        with tele_span("experiment", method=args.method, iters=args.iters,
                       seeds=args.seeds):
            result, record_aux, crowd_aux = _run_all_seeds(
                args, factory, selector, dataset, model_losses, loss_fn)
            result.regret.block_until_ready()
    if args.profile_dir:
        print(f"Profiler trace written to {args.profile_dir}")
    wall = time.perf_counter() - t0
    if telemetry is not None:
        telemetry.sample_devices()
    if record_aux is not None:
        from coda_tpu.telemetry.recorder import (
            RunRecord,
            environment_fingerprint,
            knobs_from_args,
        )

        knobs = knobs_from_args(args)
        # the replica-width hint the auto eig_mode budget saw — replay must
        # rebuild the selector with the same value or the tier (and kernel)
        # choice could silently differ from the recording
        knobs["n_parallel"] = max(1, args.seeds)
        record = RunRecord.from_result(
            result, record_aux,
            environment_fingerprint(dataset=dataset, knobs=knobs),
            run={"task": dataset.name, "synthetic": args.synthetic,
                 "data_dir": args.data_dir, "method": args.method,
                 "loss": args.loss, "iters": args.iters,
                 "seeds": args.seeds,
                 "acq_batch": getattr(args, "acq_batch", 1)},
            crowd=crowd_aux)
        record.save(args.record_dir,
                    registry=telemetry.registry if telemetry else None)
        print(f"decision record written to {args.record_dir} "
              f"(verify: python -m coda_tpu.cli replay {args.record_dir})")
    steps = args.iters * args.seeds
    q = max(1, int(getattr(args, "acq_batch", 1) or 1))
    batch_note = f", {q} labels/round" if q > 1 else ""
    print(f"{steps} selection steps in {wall:.2f}s "
          f"({steps / wall:.2f} steps/s, all seeds batched{batch_note})")

    regrets = np.asarray(result.regret)          # (seeds, iters)
    cums = np.asarray(result.cumulative_regret)  # (seeds, iters)
    stoch = np.asarray(result.stochastic)        # (seeds,)
    for s in range(args.seeds):
        print(f"seed {s}: regret@{args.iters}={regrets[s, -1]:.4f} "
              f"cumulative={cums[s, -1]:.4f} stochastic={bool(stoch[s])}")

    if not args.no_mlflow:
        from coda_tpu.tracking import TrackingStore

        store = TrackingStore(args.tracking_db)
        experiment = args.experiment_name or dataset.name
        run_name = f"{experiment}-{args.method}"
        with store.run(experiment, run_name, params=vars(args)) as parent:
            for s in range(args.seeds):
                seed_run = f"{experiment}-{args.method}-{s}"
                if store.is_finished(experiment, seed_run) and not args.force_rerun:
                    print("Seed", s, "finished. Skipping.")
                    continue
                with store.run(experiment, seed_run, parent=parent,
                               params={"seed": s, "stochastic": bool(stoch[s])}) as r:
                    r.log_metric_series("regret", regrets[s], start_step=1)
                    r.log_metric_series("cumulative regret", cums[s], start_step=1)
                    if args.debug_viz:
                        _log_debug_viz(r, selector, result, s, args.iters)
            # every seed child is logged: the reference stops after the first
            # non-stochastic seed (main.py:166-168) because there the flag
            # gates *compute*; here all seeds were already computed batched,
            # and a uniform DB layout keeps resume checks and the analysis
            # SQL (mean over children) free of special cases
            if not stoch.any():
                print("Method is not stochastic for this task.")
        if telemetry is not None:
            telemetry.flush_to_store(
                store, experiment=experiment,
                run_name=f"{run_name}-telemetry",
                params={"method": args.method})
        print(f"Logged to {args.tracking_db}")

    if telemetry is not None:
        paths = telemetry.write(extra={
            "run": {"task": dataset.name, "method": args.method,
                    "iters": args.iters, "seeds": args.seeds,
                    "wall_s": round(wall, 4)}})
        print(f"Telemetry written to {args.telemetry_dir} "
              f"({', '.join(sorted(paths))})")

    return result


def _run_all_seeds(args, factory, selector, dataset, model_losses, loss_fn):
    """Returns ``(ExperimentResult, RunTraceAux | None, CrowdAux | None)``
    — the first aux is the flight-recorder sidecar (present only under
    ``--record-dir``), the second the crowd-oracle provenance (present
    only under a noisy ``--oracle-noise``)."""
    import jax

    from coda_tpu.engine import run_seeds_compiled, run_seeds_recorded

    acq_batch = max(1, int(getattr(args, "acq_batch", 1) or 1))
    spec = getattr(args, "oracle_noise", None)
    if spec is not None:
        from coda_tpu.crowd import parse_oracle_spec

        crowd_cfg = parse_oracle_spec(spec)
        if getattr(args, "oracle_annotators", None):
            crowd_cfg = crowd_cfg._replace(
                annotators=int(args.oracle_annotators))
        if getattr(args, "oracle_reliability", None):
            crowd_cfg = crowd_cfg._replace(
                reliability=args.oracle_reliability)
        if crowd_cfg.adversarial >= crowd_cfg.annotators:
            raise SystemExit(
                "--oracle-annotators override leaves no honest annotator "
                f"(adversarial={crowd_cfg.adversarial} of "
                f"{crowd_cfg.annotators})")
        # a CLEAN spec falls through to the engine paths below — the
        # crowd wrappers would delegate to the same programs, but falling
        # through keeps the cost-capture plumbing identical too
        if not crowd_cfg.clean:
            if args.checkpoint_dir:
                raise SystemExit(
                    "--oracle-noise does not compose with "
                    "--checkpoint-dir: the chunked resumable runner "
                    "drives the perfect-oracle step; drop one flag")
            from coda_tpu.crowd import (
                run_seeds_crowd,
                run_seeds_crowd_recorded,
            )

            if getattr(args, "record_dir", None):
                result, run_aux, crowd_aux = run_seeds_crowd_recorded(
                    factory, dataset.preds, dataset.labels, crowd_cfg,
                    iters=args.iters, seeds=args.seeds, loss_fn=loss_fn,
                    trace_k=getattr(args, "record_topk", 8),
                    acq_batch=acq_batch)
                return result, run_aux, crowd_aux
            result, crowd_aux = run_seeds_crowd(
                factory, dataset.preds, dataset.labels, crowd_cfg,
                iters=args.iters, seeds=args.seeds, loss_fn=loss_fn,
                acq_batch=acq_batch)
            return result, None, crowd_aux
    if args.checkpoint_dir:
        if getattr(args, "record_dir", None):
            raise SystemExit(
                "--record-dir does not compose with --checkpoint-dir: the "
                "chunked resumable scan is a different program from the "
                "recorded one, so the record could not honor the bitwise "
                "replay contract; drop one of the flags")
        if acq_batch > 1:
            raise SystemExit(
                "--acq-batch > 1 does not compose with --checkpoint-dir: "
                "the chunked resumable runner drives the single-label "
                "step; drop one of the flags")
        # resumable path: seeds run serially, each checkpointing its chunked
        # scan under <dir>/seed_<s> (new capability; the reference's resume
        # granularity is the whole seed-run, main.py:155-157)
        from coda_tpu.engine import make_resumable_runner

        runner = make_resumable_runner(
            selector, dataset.labels, model_losses, iters=args.iters,
            every=args.checkpoint_every, dataset_id=dataset.name,
        )
        per_seed = [
            runner(s, os.path.join(args.checkpoint_dir, f"seed_{s}"))
            for s in range(args.seeds)
        ]
        import jax.numpy as jnp

        result = jax.tree.map(lambda *xs: jnp.stack(xs), *per_seed)
        return result, None, None
    if getattr(args, "record_dir", None):
        result, run_aux = run_seeds_recorded(
            factory, dataset.preds, dataset.labels,
            iters=args.iters, seeds=args.seeds, loss_fn=loss_fn,
            trace_k=getattr(args, "record_topk", 8),
            cost_label=args.method, acq_batch=acq_batch)
        return result, run_aux, None
    result = run_seeds_compiled(factory, dataset.preds, dataset.labels,
                                iters=args.iters, seeds=args.seeds,
                                loss_fn=loss_fn, cost_label=args.method,
                                acq_batch=acq_batch)
    return result, None, None


if __name__ == "__main__":
    _out = main()
    # subcommands (replay) return an int verdict code; experiment runs
    # return the ExperimentResult for in-process callers — only the former
    # is a process exit status
    if isinstance(_out, int):
        raise SystemExit(_out)
