"""coda_tpu — TPU-native active model selection.

A brand-new JAX/XLA framework with the capabilities of the PyTorch reference
``justinkay/coda`` (CODA: Consensus-Driven Active Model Selection, ICCV 2025).

Given an ``(H, N, C)`` tensor of post-softmax predictions from ``H`` candidate
models on ``N`` unlabeled points over ``C`` classes, an active model selection
method repeatedly picks a point to label, queries an oracle, updates its
beliefs, and reports its current guess of the best model.

Design stance (TPU-first, not a port):
  * selector state is a fixed-shape pytree (boolean masks, not Python lists),
  * every per-round computation is a pure jit-able function,
  * the whole labeling loop compiles to a single ``lax.scan``,
  * seeds batch under ``vmap``; the ``(H, N, C)`` tensor shards over a
    ``jax.sharding.Mesh`` (N and/or H axes) with XLA collectives over ICI.
"""

from coda_tpu.data import Dataset, make_synthetic_task
from coda_tpu.oracle import Oracle, true_losses
from coda_tpu.losses import LOSS_FNS, accuracy_loss

__version__ = "0.1.0"

__all__ = [
    "Dataset",
    "make_synthetic_task",
    "Oracle",
    "true_losses",
    "LOSS_FNS",
    "accuracy_loss",
]
