"""coda_tpu — TPU-native active model selection.

A brand-new JAX/XLA framework with the capabilities of the PyTorch reference
``justinkay/coda`` (CODA: Consensus-Driven Active Model Selection, ICCV 2025).

Given an ``(H, N, C)`` tensor of post-softmax predictions from ``H`` candidate
models on ``N`` unlabeled points over ``C`` classes, an active model selection
method repeatedly picks a point to label, queries an oracle, updates its
beliefs, and reports its current guess of the best model.

Design stance (TPU-first, not a port):
  * selector state is a fixed-shape pytree (boolean masks, not Python lists),
  * every per-round computation is a pure jit-able function,
  * the whole labeling loop compiles to a single ``lax.scan``,
  * seeds batch under ``vmap``; the ``(H, N, C)`` tensor shards over a
    ``jax.sharding.Mesh`` (N and/or H axes) with XLA collectives over ICI.
"""

import jax as _jax

# Sharding-invariant RNG, set before any program traces. The default
# (non-partitionable) threefry's bit-generation gets partitioned by GSPMD
# with shard-local counter offsets when its output is sharded, so the SAME
# key could yield DIFFERENT bits in a sharded vs unsharded program — which
# silently diverged sharded experiment traces wherever randomness feeds an
# adaptive decision (the tie-break draws in `masked_argmax_tiebreak`; the
# former `test_suite_sharded_task_matches_unsharded` failure, NOTES_r07).
# Partitionable threefry computes bits as a sharding-oblivious function of
# (key, position), restoring trace parity across mesh layouts.
_jax.config.update("jax_threefry_partitionable", True)

from coda_tpu.data import Dataset, make_synthetic_task
from coda_tpu.oracle import Oracle, true_losses
from coda_tpu.losses import LOSS_FNS, accuracy_loss

__version__ = "0.1.0"

__all__ = [
    "Dataset",
    "make_synthetic_task",
    "Oracle",
    "true_losses",
    "LOSS_FNS",
    "accuracy_loss",
]
