"""The crowd experiment loop: joint (model, annotator) posterior scan.

The engine's program is ``scan(select -> oracle -> update -> best)`` with
the oracle a perfect table lookup. Here the oracle is a crowd: each round
the chosen point's TRUE label seeds a deterministic vote draw from the
annotator pool, the Dawid-Skene reliability posterior aggregates the
votes into an applied label + reliability weight, and the selector's
weighted update (``update_w`` / the fused ``update_qw``) applies it. The
reliability posterior rides the scan carry NEXT TO the model posterior —
both are updated jointly every round, with no host round-trip.

Key choreography is the engine's exactly: ``k_init, k_prior, k_scan =
split(key, 3)``; per round ``k_sel, k_best = split(k)``. The crowd's vote
randomness comes from ``fold_in(k, CROWD_SALT)`` — a key the plain
program never consumes — so select/best see the identical stream.

**Clean configs run the engine's own program**: ``cfg.clean`` is a
static Python branch delegating to ``engine/loop.py`` verbatim (same
functions, same jaxpr), which is what pins the clean-oracle rung bitwise
at every layer above (records, replay, serve).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.crowd.oracle import CROWD_SALT, CrowdConfig, make_annotators, \
    sample_votes
from coda_tpu.crowd.reliability import aggregate_votes, annotator_accuracy, \
    init_reliability
from coda_tpu.engine.loop import (
    ExperimentResult,
    RunTraceAux,
    _validate_rounds,
    build_experiment_fn,
    build_recording_experiment_fn,
    key_bits,
    make_round_trace,
)
from coda_tpu.losses import accuracy_loss
from coda_tpu.oracle import true_losses as compute_true_losses
from coda_tpu.selectors.protocol import Selector


class CrowdAux(NamedTuple):
    """Per-round crowd provenance (leading axis = round; with acq_batch q
    the first three carry a trailing (q,) answer axis)."""

    oracle_label: jnp.ndarray      # ground-truth label of the chosen point
    applied_label: jnp.ndarray     # the aggregated label the update saw
    label_weight: jnp.ndarray      # its reliability weight in [0, 1]
    annotator_accuracy: jnp.ndarray  # (T, A) posterior-mean accuracies


def _require_weighted(selector: Selector) -> None:
    if selector.update_w is None:
        raise ValueError(
            f"selector {selector.name!r} has no reliability-weighted "
            "update (update_w); the crowd oracle needs one — run the "
            "clean oracle instead")


def make_crowd_step_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    cfg: CrowdConfig,
    confusions: jnp.ndarray,
    trace_k: int = 0,
    acq_batch: int = 1,
):
    """One crowd labeling round as a pure scan step over the carry
    ``(selector state, reliability state, cumulative regret)``.

    Mirrors ``engine.loop.make_step_fn`` — same key splits, same named
    scopes, same output tuple — plus a :class:`CrowdAux` entry appended
    AFTER the engine outputs (and after the optional RoundTrace), so the
    engine's harvest order is untouched.
    """
    assert not cfg.clean, "clean configs run the engine step (bitwise pin)"
    _require_weighted(selector)
    best_loss = model_losses.min()

    def crowd_answer(rel, k, true_class, j: int = 0):
        """One answer's votes + aggregation (fold_in keeps the engine's
        select/best key stream untouched; j salts q-wide answers)."""
        k_crowd = jax.random.fold_in(k, CROWD_SALT + j)
        ann_ids, responses, answered = sample_votes(
            k_crowd, confusions, true_class, cfg)
        return aggregate_votes(rel, ann_ids, responses, answered, cfg)

    if acq_batch > 1:
        from coda_tpu.selectors.batch import resolve_batch_wfns

        sel_q, upd_qw = resolve_batch_wfns(selector, acq_batch)

        def step_q(carry, k):
            state, rel, cum = carry
            k_sel, k_best = jax.random.split(k)
            with jax.named_scope("select_q"):
                res = sel_q(state, k_sel)
            tcs = labels[res.idx]                      # (q,) ground truth
            zs, ws = [], []
            with jax.named_scope("crowd"):
                # the reliability posterior chains through the q answers
                # (q is static and small — the scatter_rows idiom)
                for j in range(acq_batch):
                    z_j, w_j, rel = crowd_answer(rel, k, tcs[j], j)
                    zs.append(z_j)
                    ws.append(w_j)
                applied = jnp.stack(zs)
                weights = jnp.stack(ws)
            with jax.named_scope("update_qw"):
                state = upd_qw(state, res.idx, applied, res.prob, weights)
            with jax.named_scope("best"):
                best, b_stoch = selector.best(state, k_best)
            regret = model_losses[best] - best_loss
            cum = cum + acq_batch * regret             # label-weighted
            outs = (res.idx, applied, best, regret, cum, res.prob,
                    res.stochastic | b_stoch)
            if trace_k:
                with jax.named_scope("record"):
                    outs = outs + (make_round_trace(selector, res, state,
                                                    k, trace_k),)
            aux = CrowdAux(oracle_label=tcs, applied_label=applied,
                           label_weight=weights,
                           annotator_accuracy=annotator_accuracy(rel))
            return (state, rel, cum), outs + (aux,)

        return step_q

    def step(carry, k):
        state, rel, cum = carry
        k_sel, k_best = jax.random.split(k)
        with jax.named_scope("select"):
            res = selector.select(state, k_sel)
        tc = labels[res.idx]                           # ground truth
        with jax.named_scope("crowd"):
            applied, weight, rel = crowd_answer(rel, k, tc)
        with jax.named_scope("update_w"):
            state = selector.update_w(state, res.idx, applied, res.prob,
                                      weight)
        with jax.named_scope("best"):
            best, b_stoch = selector.best(state, k_best)
        regret = model_losses[best] - best_loss
        cum = cum + regret
        outs = (res.idx, applied, best, regret, cum, res.prob,
                res.stochastic | b_stoch)
        if trace_k:
            with jax.named_scope("record"):
                outs = outs + (make_round_trace(selector, res, state, k,
                                                trace_k),)
        aux = CrowdAux(oracle_label=tc, applied_label=applied,
                       label_weight=weight,
                       annotator_accuracy=annotator_accuracy(rel))
        return (state, rel, cum), outs + (aux,)

    return step


def _crowd_experiment(selector, labels, model_losses, cfg, iters,
                      trace_k, acq_batch):
    """The shared scan driver behind both build_* variants."""
    best_loss = model_losses.min()
    N = labels.shape[0]
    _validate_rounds(selector, N, iters, acq_batch)
    if isinstance(labels, jax.core.Tracer):
        raise ValueError(
            "the crowd loop needs concrete labels to size the annotator "
            "confusions (got a traced labels array)")
    import numpy as np

    # host-side reduction: labels are a closed-over CONCRETE array (the
    # guard above), and a jnp.max here would trace under the jit wrapper
    n_classes = int(np.asarray(labels).max()) + 1
    confusions = make_annotators(cfg, n_classes)
    step = make_crowd_step_fn(selector, labels, model_losses, cfg,
                              confusions, trace_k=trace_k,
                              acq_batch=acq_batch)

    def experiment(key: jax.Array):
        k_init, k_prior, k_scan = jax.random.split(key, 3)
        state0 = selector.init(k_init)
        best0, stoch0 = selector.best(state0, k_prior)
        regret0 = model_losses[best0] - best_loss
        rel0 = init_reliability(cfg, n_classes)

        keys = jax.random.split(k_scan, iters)
        carry0 = (state0, rel0, jnp.asarray(0.0, jnp.float32))
        if trace_k:
            (_, _, _), (idxs, tcs, bests, regrets, cums, probs, stoch,
                        trace, aux) = lax.scan(step, carry0, keys)
        else:
            (_, _, _), (idxs, tcs, bests, regrets, cums, probs, stoch,
                        aux) = lax.scan(step, carry0, keys)
            trace = None
        result = ExperimentResult(
            chosen_idx=idxs,
            true_class=tcs,
            best_model=bests,
            regret=regrets,
            cumulative_regret=cums,
            select_prob=probs,
            regret_at_0=regret0,
            stochastic=stoch.any() | stoch0
            | jnp.asarray(selector.always_stochastic),
        )
        if trace is None:
            return result, aux
        run_aux = RunTraceAux(trace=trace, root_key=key_bits(key),
                              init_key=key_bits(k_init),
                              prior_key=key_bits(k_prior))
        return result, run_aux, aux

    return experiment


def build_crowd_experiment_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    cfg: CrowdConfig,
    iters: int = 100,
    acq_batch: int = 1,
) -> Callable:
    """``key -> (ExperimentResult, CrowdAux)`` for one seed. A clean
    config returns ``(engine result, None)`` — the engine's own program,
    bitwise (the crowd machinery never traces)."""
    if cfg.clean:
        base = build_experiment_fn(selector, labels, model_losses, iters,
                                   acq_batch=acq_batch)
        return lambda key: (base(key), None)
    return _crowd_experiment(selector, labels, model_losses, cfg, iters,
                             trace_k=0, acq_batch=acq_batch)


def build_recording_crowd_experiment_fn(
    selector: Selector,
    labels: jnp.ndarray,
    model_losses: jnp.ndarray,
    cfg: CrowdConfig,
    iters: int = 100,
    trace_k: int = 8,
    acq_batch: int = 1,
) -> Callable:
    """``key -> (ExperimentResult, RunTraceAux, CrowdAux)`` — the
    flight-recorder variant; clean configs run the engine's recording
    program with ``CrowdAux = None``."""
    if cfg.clean:
        base = build_recording_experiment_fn(
            selector, labels, model_losses, iters, trace_k=trace_k,
            acq_batch=acq_batch)

        def clean(key):
            result, aux = base(key)
            return result, aux, None

        return clean
    N = labels.shape[0]
    trace_k = max(1, min(int(trace_k), N))
    return _crowd_experiment(selector, labels, model_losses, cfg, iters,
                             trace_k=trace_k, acq_batch=acq_batch)


def _run_crowd(selector_factory, preds, labels, cfg, iters, seeds,
               loss_fn, trace_k, acq_batch):
    labels = jnp.asarray(labels)

    def fn(preds_arg, keys):
        sel = selector_factory(preds_arg)
        losses = compute_true_losses(preds_arg, labels, loss_fn)
        exp = (build_recording_crowd_experiment_fn(
                   sel, labels, losses, cfg, iters, trace_k=trace_k,
                   acq_batch=acq_batch)
               if trace_k else
               build_crowd_experiment_fn(sel, labels, losses, cfg, iters,
                                         acq_batch=acq_batch))
        if keys.shape[0] == 1:
            return jax.tree.map(lambda x: jnp.asarray(x)[None], exp(keys[0]))
        return jax.vmap(exp)(keys)

    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(seeds)])
    return jax.jit(fn)(preds, keys)


def run_seeds_crowd(
    selector_factory: Callable[[jnp.ndarray], Selector],
    preds: jnp.ndarray,
    labels: jnp.ndarray,
    cfg: CrowdConfig,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
    acq_batch: int = 1,
):
    """All seeds of the crowd experiment: ``(ExperimentResult,
    CrowdAux | None)``, seed axis leading. The labels stay CONCRETE
    (they size the annotator pool's confusion tensor at trace time)."""
    return _run_crowd(selector_factory, preds, labels, cfg, iters, seeds,
                      loss_fn, trace_k=0, acq_batch=acq_batch)


def run_seeds_crowd_recorded(
    selector_factory: Callable[[jnp.ndarray], Selector],
    preds: jnp.ndarray,
    labels: jnp.ndarray,
    cfg: CrowdConfig,
    iters: int = 100,
    seeds: int = 5,
    loss_fn: Callable = accuracy_loss,
    trace_k: int = 8,
    acq_batch: int = 1,
):
    """:func:`run_seeds_crowd` with the flight recorder on:
    ``(ExperimentResult, RunTraceAux, CrowdAux | None)``."""
    return _run_crowd(selector_factory, preds, labels, cfg, iters, seeds,
                      loss_fn, trace_k=max(1, trace_k),
                      acq_batch=acq_batch)
