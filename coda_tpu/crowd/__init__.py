"""Crowd oracle subsystem: noisy, abstaining, asynchronous labelers.

The rest of the stack assumes ONE clean synchronous oracle
(``coda_tpu/oracle.py``). This package is the production-labeling tier:

  * :mod:`coda_tpu.crowd.oracle` — the crowd model: per-annotator
    confusion matrices from a seeded generator (honest, adversarial),
    the oracle verb vocabulary (answer / abstain / defer / poison), a
    device-side vote sampler for the compiled scan and a host-side
    deterministic sampler (:class:`HostCrowdSampler`) for the serve
    front door and the loadgen;
  * :mod:`coda_tpu.crowd.reliability` — the jointly-learned
    Dawid-Skene-style annotator-reliability posterior (per-annotator
    confusion Dirichlets carried in the scan), its vote aggregation,
    and the trust gate that degrades to majority-vote weighting until
    the posterior has seen enough votes;
  * :mod:`coda_tpu.crowd.loop` — the crowd experiment loop: the
    engine's ``lax.scan`` with (selector state, reliability state)
    jointly carried, answers applied through the selectors'
    reliability-weighted updates (``update_w``/``update_qw``). A clean
    config routes through the UNMODIFIED engine program — bitwise the
    plain run.
"""

from coda_tpu.crowd.oracle import (  # noqa: F401
    CrowdConfig,
    HostCrowdSampler,
    make_annotators,
    parse_oracle_spec,
    sample_votes,
)
from coda_tpu.crowd.reliability import (  # noqa: F401
    ReliabilityState,
    aggregate_votes,
    annotator_accuracy,
    init_reliability,
)
from coda_tpu.crowd.loop import (  # noqa: F401
    build_crowd_experiment_fn,
    build_recording_crowd_experiment_fn,
    make_crowd_step_fn,
    run_seeds_crowd,
    run_seeds_crowd_recorded,
)
