"""The crowd model: seeded annotator pools and the oracle verb protocol.

An annotator is a ``(C, C)`` row-stochastic confusion matrix: row ``z``
is the response distribution when the true class is ``z``. Honest
annotators put ``acc`` on the diagonal and spread the rest uniformly;
adversarial (poisoned) annotators put their mass on the SHIFTED diagonal
``(z + 1) % C`` — a systematic mislabeler the reliability posterior must
learn to down-weight, not just average out.

Verbs (the protocol beyond "answer now"):

  * ``answer``  — a label drawn from the annotator's confusion row;
  * ``abstain`` — no label this round (the slot stays open; a weighted
    update with w=0 is the structural no-op fallback when every vote
    abstains);
  * ``defer``   — the answer arrives ``k`` rounds LATE, out of order
    (host-side delivery semantics: the serve layer parks the slot and
    the request-id dedupe makes redelivery idempotent);
  * ``poison``  — the adversarial answer family above (also injectable
    out-of-band at the serve answer site via ``serve/faults.py``'s
    ``oracle_poison``).

Everything is deterministic: the device-side sampler
(:func:`sample_votes`) derives from the scan round's PRNG key via a
fold-in salt (so the selection/best key stream of the clean run is
untouched), and the host-side :class:`HostCrowdSampler` uses
counter-addressed SHA-256 draws in the style of ``serve/faults.py`` —
same (seed, session, round, slot) always produces the same verb.
"""

from __future__ import annotations

import hashlib
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# fold-in salt separating the crowd's vote randomness from the engine's
# select/best key stream (engine choreography: k_sel, k_best = split(k);
# the crowd draws from fold_in(k, SALT) so a clean config consumes the
# exact key material of the plain run)
CROWD_SALT = 0xC403D


class CrowdConfig(NamedTuple):
    """One crowd-oracle configuration (parsed from ``--oracle-noise``)."""

    spec: str = "clean"          # the original spec string (the knob)
    clean: bool = True           # clean => the plain-oracle program runs
    annotators: int = 8          # pool size A
    votes: int = 3               # votes drawn per labeled item
    acc_lo: float = 0.55         # honest-annotator accuracy range
    acc_hi: float = 0.95
    abstain: float = 0.0         # per-vote abstention probability
    adversarial: int = 0         # poisoned annotators (last slots of the pool)
    reliability: str = "learned"  # 'learned' (DS posterior) | 'majority'
    trust_votes: float = 32.0    # pool votes before the learned gate opens
    defer: float = 0.0           # per-answer deferral probability (serve verb)
    defer_depth: int = 4         # max rounds an answer arrives late
    seed: int = 0                # the annotator-pool / vote-stream seed


def parse_oracle_spec(spec: Optional[str]) -> CrowdConfig:
    """``None``/``'clean'`` -> the clean config; otherwise comma-separated
    ``k=v`` pairs, e.g.
    ``annotators=8,votes=3,acc=0.55:0.95,abstain=0.1,adversarial=1,
    trust=32,defer=0.2:4,reliability=learned,seed=0``.
    Fails loudly on unknown keys — the CLI forwards the string verbatim.
    """
    if spec is None or spec == "clean":
        return CrowdConfig(spec="clean", clean=True)
    cfg: dict = {"spec": spec, "clean": False}
    for kv in filter(None, (s.strip() for s in spec.split(","))):
        if "=" not in kv:
            raise ValueError(f"oracle-noise param {kv!r} is not key=value")
        k, v = kv.split("=", 1)
        if k == "annotators":
            cfg["annotators"] = int(v)
        elif k == "votes":
            cfg["votes"] = int(v)
        elif k == "acc":
            lo, _, hi = v.partition(":")
            cfg["acc_lo"] = float(lo)
            cfg["acc_hi"] = float(hi or lo)
        elif k == "abstain":
            cfg["abstain"] = float(v)
        elif k == "adversarial":
            cfg["adversarial"] = int(v)
        elif k == "trust":
            cfg["trust_votes"] = float(v)
        elif k == "defer":
            p, _, d = v.partition(":")
            cfg["defer"] = float(p)
            if d:
                cfg["defer_depth"] = int(d)
        elif k == "reliability":
            if v not in ("learned", "majority"):
                raise ValueError(
                    f"oracle-noise reliability={v!r} (use 'learned' or "
                    "'majority')")
            cfg["reliability"] = v
        elif k == "seed":
            cfg["seed"] = int(v)
        else:
            raise ValueError(
                f"unknown oracle-noise key {k!r} in {spec!r}")
    out = CrowdConfig(**cfg)
    if out.annotators < 1 or out.votes < 1:
        raise ValueError(f"oracle-noise needs annotators >= 1 and "
                         f"votes >= 1 (got {out.annotators}, {out.votes})")
    if out.adversarial >= out.annotators:
        raise ValueError(
            f"adversarial={out.adversarial} must leave at least one "
            f"honest annotator (pool of {out.annotators})")
    if not (0.0 <= out.abstain < 1.0) or not (0.0 <= out.defer < 1.0):
        raise ValueError("abstain/defer rates must be in [0, 1)")
    return out


def planted_accuracies(cfg: CrowdConfig) -> np.ndarray:
    """The pool's (A,) diagonal accuracies — honest annotators drawn
    uniformly from ``[acc_lo, acc_hi]`` by the seeded generator,
    adversarial slots at ``acc_lo`` ON THE SHIFTED DIAGONAL (their true-
    diagonal accuracy is the uniform remainder). Host-side numpy: built
    once per experiment, the same values :func:`make_annotators` bakes
    into the confusion tensor."""
    rng = np.random.RandomState(cfg.seed)
    return cfg.acc_lo + (cfg.acc_hi - cfg.acc_lo) * rng.rand(cfg.annotators)


def make_annotators(cfg: CrowdConfig, n_classes: int) -> jnp.ndarray:
    """The pool's ``(A, C, C)`` row-stochastic confusion matrices.

    Deterministic in ``cfg.seed``. The last ``cfg.adversarial`` slots are
    poisoned: their accuracy mass sits on ``(z + 1) % C`` instead of the
    diagonal — a consistent wrong answer, the hardest case for naive
    majority voting and the reason the reliability posterior exists.
    """
    A, C = cfg.annotators, n_classes
    acc = planted_accuracies(cfg)                                # (A,)
    eye = np.eye(C)
    shift = np.eye(C)[:, list(range(1, C)) + [0]]                # (z+1)%C
    off = (1.0 - acc)[:, None, None] / max(C - 1, 1)
    conf = acc[:, None, None] * eye[None] + off * (1.0 - eye[None])
    if cfg.adversarial:
        bad = (acc[:, None, None] * shift[None]
               + off * (1.0 - shift[None]))
        is_bad = np.arange(A)[:, None, None] >= (A - cfg.adversarial)
        conf = np.where(is_bad, bad, conf)
    return jnp.asarray(conf, jnp.float32)


def sample_votes(key, confusions: jnp.ndarray, true_class,
                 cfg: CrowdConfig):
    """Draw one round's crowd response inside the compiled scan.

    Returns ``(ann_ids (V,) int32, responses (V,) int32, answered (V,)
    bool)`` — ``V = cfg.votes`` annotators drawn uniformly with
    replacement, each answering from its confusion row for
    ``true_class`` or abstaining. Abstained slots keep a valid class id
    (their response draw) but ``answered`` is False and every consumer
    masks on it.
    """
    V = cfg.votes
    k_who, k_resp, k_abst = jax.random.split(key, 3)
    ann_ids = jax.random.randint(k_who, (V,), 0, cfg.annotators,
                                 dtype=jnp.int32)
    rows = confusions[ann_ids, true_class, :]                    # (V, C)
    responses = jax.random.categorical(
        k_resp, jnp.log(jnp.clip(rows, 1e-30, None)), axis=-1
    ).astype(jnp.int32)
    answered = (jax.random.uniform(k_abst, (V,)) >= cfg.abstain
                if cfg.abstain > 0.0 else jnp.ones((V,), bool))
    return ann_ids, responses, answered


def _draw(seed: int, *fields) -> float:
    """Counter-addressed uniform in [0, 1): a pure function of
    ``(seed, fields...)`` — the ``serve/faults.py`` determinism idiom, so
    a host-side crowd run replays exactly from its spec."""
    h = hashlib.sha256(
        ":".join([str(seed)] + [str(f) for f in fields]).encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class HostCrowdSampler:
    """Host-side deterministic crowd: the serve/loadgen half of the verb
    protocol. Where the compiled scan samples votes from the round key,
    the serve front door receives one answer per (session, round, slot) —
    this class decides, reproducibly, WHAT that answer is and WHEN it
    arrives.

    ``answer(session, round, slot, true_label)`` returns a dict::

        {"verb": "answer" | "abstain",
         "label": int,          # the (possibly noisy) response
         "annotator": int,      # who answered
         "defer": int}          # rounds late (0 = deliver now)

    A deferred answer is the SAME answer delivered late — the caller
    (loadgen's ``--oracle-noise`` mode) holds it for ``defer`` rounds
    and posts it out of order; the serve layer's slot parking plus
    request-id dedupe make the delivery order immaterial.
    """

    def __init__(self, cfg: CrowdConfig, n_classes: int):
        self.cfg = cfg
        self.n_classes = n_classes
        self.confusions = np.asarray(make_annotators(cfg, n_classes))

    def answer(self, session: str, round_idx: int, slot: int,
               true_label: int, attempt: int = 0) -> dict:
        # `attempt` re-addresses the draw when a slot's annotator
        # abstained and the caller re-requests the item (a different
        # worker picks it up) — still a pure function of its key
        cfg = self.cfg
        key = (session, round_idx, slot, attempt)
        ann = int(_draw(cfg.seed, "who", *key) * cfg.annotators)
        ann = min(ann, cfg.annotators - 1)
        if cfg.clean:
            return {"verb": "answer", "label": int(true_label),
                    "annotator": ann, "defer": 0}
        if _draw(cfg.seed, "abstain", *key) < cfg.abstain:
            return {"verb": "abstain", "label": int(true_label),
                    "annotator": ann, "defer": 0}
        # invert the annotator's confusion row CDF at a deterministic draw
        row = self.confusions[ann, int(true_label)]
        u = _draw(cfg.seed, "resp", *key)
        label = int(np.searchsorted(np.cumsum(row), u))
        label = min(label, self.n_classes - 1)
        defer = 0
        if cfg.defer > 0.0 and _draw(cfg.seed, "defer", *key) < cfg.defer:
            defer = 1 + int(
                _draw(cfg.seed, "depth", *key) * cfg.defer_depth)
        return {"verb": "answer", "label": label, "annotator": ann,
                "defer": defer}
