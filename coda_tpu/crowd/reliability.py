"""The jointly-learned annotator-reliability posterior (Dawid-Skene).

Each annotator ``a`` carries a Dirichlet posterior over its ``(C, C)``
confusion matrix — ``counts[a, z, r]`` is the (soft) number of times
annotator ``a`` answered ``r`` when the aggregated label said ``z``,
plus a symmetric Laplace prior. That is the classic Dawid-Skene model
(per-annotator confusion, EM over items) restated as carried-fit
statistics in the PR-14 surrogate style: a closed-form E-step per round
inside the scan, no host round-trips, the whole state a small pytree
riding next to the model posterior.

Per labeling round with votes ``(a_v, r_v, answered_v)``:

  1. **E-step (aggregate)**: ``log p(z) = Σ_v answered_v ·
     log ĉonf_a_v[z, r_v] + log(1 + tally_z)`` — the vote likelihood
     under the posterior-mean confusion ``ĉonf = counts /
     counts.sum(-1)``, anchored by the majority tally as a log-prior
     (the online restatement of batch Dawid-Skene's majority-vote EM
     initialization; see the inline comment for why the unanchored
     form collapses at cold start). The aggregated label is the
     argmax, its posterior mass the *learned* reliability weight.
  2. **Trust gate**: until the pool has accumulated
     ``cfg.trust_votes`` answered votes, the learned estimate is one
     noisy matrix judging another — the gate degrades aggregation to
     MAJORITY VOTE (label = modal response, weight = modal fraction)
     so an unconverged posterior can never poison the selection argmax.
     Both branches are computed and a scalar ``jnp.where`` picks — the
     lax.cond-under-vmap idiom the codebase's other gates use.
  3. **M-step (update)**: ``counts[a_v, z, r_v] += answered_v ·
     p(z)`` — the soft-assignment increment, so confident rounds teach
     more than ambiguous ones.

All-abstain rounds aggregate to weight 0 — combined with the weighted
update's ``w=0`` structural no-op, the model posterior is untouched
while the round still consumes its point.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from coda_tpu.crowd.oracle import CrowdConfig


class ReliabilityState(NamedTuple):
    """The carried annotator posterior (a scan-friendly pytree)."""

    counts: jnp.ndarray   # (A, C, C) f32 — confusion Dirichlet counts
    n_votes: jnp.ndarray  # scalar f32 — answered votes seen by the pool


def init_reliability(cfg: CrowdConfig, n_classes: int) -> ReliabilityState:
    """Symmetric Laplace prior (1 pseudo-count per cell): proper, and
    the posterior-mean confusion starts uniform — no annotator is
    trusted or distrusted before evidence arrives."""
    A, C = cfg.annotators, n_classes
    return ReliabilityState(
        counts=jnp.ones((A, C, C), jnp.float32),
        n_votes=jnp.asarray(0.0, jnp.float32),
    )


def annotator_accuracy(rel: ReliabilityState) -> jnp.ndarray:
    """Posterior-mean diagonal accuracy per annotator, (A,) — the
    quantity the robustness gate compares against the planted pool."""
    conf = rel.counts / rel.counts.sum(-1, keepdims=True)
    return jnp.diagonal(conf, axis1=-2, axis2=-1).mean(-1)


def accuracy_movement(prev_acc, acc) -> float:
    """Mean |Δ posterior-mean accuracy| per annotator between two reads
    of :func:`annotator_accuracy` — the drift observable the decision-
    quality plane's ``crowd_reliability`` detector consumes
    (``telemetry/quality.py``): a converged crowd holds this near 0;
    a sustained shift means the annotator pool changed under the fleet
    (churn, degradation, or an attack ramping up)."""
    import numpy as np

    prev_acc = np.asarray(prev_acc, np.float64)
    acc = np.asarray(acc, np.float64)
    return float(np.abs(acc - prev_acc).mean())


def aggregate_votes(rel: ReliabilityState, ann_ids, responses, answered,
                    cfg: CrowdConfig):
    """One round's E-step + trust gate + M-step.

    ``ann_ids``/``responses``/``answered`` are the (V,) vote arrays of
    :func:`coda_tpu.crowd.oracle.sample_votes`. Returns
    ``(label, weight, rel')`` — the aggregated label (int32 scalar), its
    reliability weight in [0, 1] (f32 scalar, 0 when every vote
    abstained), and the updated posterior.
    """
    C = rel.counts.shape[-1]
    V = ann_ids.shape[0]
    ans_f = answered.astype(jnp.float32)                          # (V,)
    n_ans = ans_f.sum()

    # -- majority-vote tally ----------------------------------------------
    onehot = jax.nn.one_hot(responses, C, dtype=jnp.float32)      # (V, C)
    tally = (ans_f[:, None] * onehot).sum(0)                      # (C,)
    z_maj = jnp.argmax(tally).astype(jnp.int32)  # ties -> smallest class
    w_maj = tally[z_maj] / jnp.clip(n_ans, 1.0, None)

    # -- learned (Dawid-Skene) aggregation --------------------------------
    conf = rel.counts / rel.counts.sum(-1, keepdims=True)         # (A, C, C)
    # log-likelihood of each hypothesized true label z given the votes
    ll_votes = jnp.log(jnp.clip(conf[ann_ids, :, responses],
                                1e-30, None))                     # (V, C)
    ll = (ans_f[:, None] * ll_votes).sum(0)                       # (C,)
    # majority-anchored E-step: a near-uniform confusion posterior (the
    # Laplace-prior cold start) has a FLAT likelihood whose argmax is a
    # constant class — and teaching the M-step with that flat posterior
    # keeps the confusions uniform forever (a self-reinforcing
    # collapse). Anchoring with the vote tally as a log-prior makes the
    # cold-start DS label degrade to majority vote, while the
    # likelihood term (which grows with the sharpness of the learned
    # confusions, not with round count) dominates once the posterior
    # has real evidence — the classic majority-vote initialization of
    # batch Dawid-Skene EM, restated for the online carried-fit form.
    ll = ll + jnp.log1p(tally)
    p_z = jax.nn.softmax(ll)                                      # (C,)
    z_ds = jnp.argmax(p_z).astype(jnp.int32)
    w_ds = p_z[z_ds]

    # -- trust gate --------------------------------------------------------
    trusted = (rel.n_votes >= cfg.trust_votes) if \
        cfg.reliability == "learned" else jnp.asarray(False)
    label = jnp.where(trusted, z_ds, z_maj)
    weight = jnp.where(trusted, w_ds, w_maj)
    # all-abstain round: no evidence at all -> weight 0 (the update's
    # structural no-op); the label falls back to the majority slot's
    # argmax over an all-zero tally (class 0) — immaterial under w=0
    weight = jnp.where(n_ans > 0, weight, 0.0)

    # -- M-step: soft-assignment counts update ----------------------------
    # teach with the distribution of the branch actually APPLIED, so the
    # posterior and the model update never disagree about the round
    p_teach = jnp.where(trusted, p_z, jax.nn.one_hot(z_maj, C))
    inc = ans_f[:, None] * jnp.broadcast_to(p_teach, (V, C))      # (V, C)
    counts = rel.counts.at[ann_ids, :, responses].add(inc)
    rel2 = ReliabilityState(counts=counts, n_votes=rel.n_votes + n_ans)
    return label, weight, rel2
