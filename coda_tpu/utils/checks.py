"""Numeric sanity checks (the framework's always-available "numeric sanitizer").

The reference enforces correctness at runtime with NaN/Inf and
probability-validity asserts on every intermediate (reference
``coda/util.py:17-39``, gated by ``_DEBUG`` at ``coda/coda.py:10``). Those are
host-side asserts; under jit they would force a device sync per intermediate.

Here the same invariants exist in two forms:
  * eager checks (``check_finite`` / ``check_prob``) for tests and the
    host-driven demo path, raising like the reference, and
  * ``jit_check_finite`` — a jit-safe variant using ``jax.debug.callback``,
    wired into the P(best) kernel (``coda_tpu/ops/pbest.py``) and enabled
    with ``CODA_TPU_DEBUG_CHECKS=1``; a no-op (zero trace cost) otherwise.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

# Mirrors the reference's module-level _DEBUG switch; off by default in the
# compiled path because every check is a host callback.
DEBUG_CHECKS = os.environ.get("CODA_TPU_DEBUG_CHECKS", "0") == "1"


def check_finite(t, name: str = "tensor", raise_err: bool = True) -> None:
    """Raise (or warn) if ``t`` contains NaN/Inf."""
    arr = np.asarray(t)
    bad = ~np.isfinite(arr)
    if bad.any():
        msg = (
            f"[NUMERIC ERROR] {name} has {int(bad.sum())} bad values "
            f"(NaN/Inf) out of {arr.size} "
            f"min={np.nanmin(arr):.3g}, max={np.nanmax(arr):.3g}"
        )
        if raise_err:
            raise FloatingPointError(msg)
        print(msg)


def check_prob(p, name: str = "prob", eps: float = 1e-12) -> None:
    """Raise if ``p`` is not a valid probability distribution over its last axis."""
    check_finite(p, name)
    arr = np.asarray(p)
    if (arr < -eps).any():
        raise FloatingPointError(f"{name} has negatives")
    s = arr.sum(-1)
    if not np.isfinite(s).all():
        raise FloatingPointError(f"{name} sum is nan/inf")
    if (np.abs(s - 1) > 1e-4).any():
        print(
            f"[WARN] {name} rows not normalised: min sum={s.min():.4f}, "
            f"max sum={s.max():.4f}"
        )


def _host_check(arr: np.ndarray, name: str) -> None:
    check_finite(arr, str(name))


def jit_check_finite(t: jnp.ndarray, name: str) -> None:
    """jit-safe finite check via host callback; no-op unless DEBUG_CHECKS."""
    if DEBUG_CHECKS:
        jax.debug.callback(_host_check, t, name)
