"""Debug visualization: bar charts of P(best) / acquisition scores.

Capability parity with the reference's debug renderer (reference
``coda/util.py:42-66`` ``plot_bar`` and the ``_DEBUG_VIZ`` hooks at
``coda/coda.py:299-303,337-341`` that log EIG / P(best) bar charts per step).
Host-side only — figures are rendered after compiled runs finish, never
inside jit. Matplotlib uses the Agg backend so this works headless.
"""

from __future__ import annotations

import io

import numpy as np


def plot_bar(values, title: str = "", highlight: int | None = None,
             xlabel: str = "", ylabel: str = ""):
    """Bar chart of a 1-D score vector -> matplotlib Figure.

    ``highlight`` draws one bar (e.g. the argmax / chosen model) in a
    distinct color, like the reference's chosen-bar styling.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    values = np.asarray(values)
    colors = ["tab:blue"] * len(values)
    if highlight is not None:
        colors[int(highlight)] = "tab:orange"
    fig, ax = plt.subplots(figsize=(max(4, len(values) * 0.35), 3))
    ax.bar(np.arange(len(values)), values, color=colors)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    fig.tight_layout()
    return fig


def plot_series(series, title: str = "", xlabel: str = "step",
                ylabel: str = "", labels=None):
    """Line plot of one or more per-step traces (e.g. regret curves)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    arr = np.atleast_2d(np.asarray(series))
    fig, ax = plt.subplots(figsize=(5, 3))
    for i, row in enumerate(arr):
        ax.plot(np.arange(1, len(row) + 1), row,
                label=None if labels is None else labels[i])
    if labels is not None:
        ax.legend(fontsize=8)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    fig.tight_layout()
    return fig


def fig_to_png(fig) -> bytes:
    """Rasterize a figure to PNG bytes (for artifact logging)."""
    buf = io.BytesIO()
    fig.savefig(buf, format="png", dpi=120)
    import matplotlib.pyplot as plt

    plt.close(fig)
    return buf.getvalue()
