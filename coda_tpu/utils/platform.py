"""One shared site-hook workaround for every CLI entry point.

This environment's site hook force-registers an accelerator platform and
overrides ``JAX_PLATFORMS``; when that device tunnel is wedged, any jax
array op hangs the process. Pinning must happen in-process *before any
backend initializes* — which is why every entry point defers its jax
imports and calls :func:`pin_platform` first.
"""

from __future__ import annotations

from typing import Optional


def pin_platform(platform: Optional[str]) -> None:
    """Force a jax platform (e.g. ``"cpu"``/``"tpu"``); no-op when None."""
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)


def enable_compilation_cache(cache_dir: Optional[str]) -> None:
    """Point jax's persistent compilation cache at ``cache_dir``.

    A restarted process deserializes executables instead of recompiling —
    NOTES_r08 measured cache-deserialized executables 3.4x faster to obtain
    than fresh in-process compiles, which is what makes the serve warm pool
    a cold-start lever and not just a steady-state one. The two threshold
    knobs are zeroed because this framework's hot programs (slab steps,
    selector inits) are exactly the small-but-recompiled-often executables
    the defaults would skip. No-op when ``cache_dir`` is falsy; must run
    before the first compile to cover everything (later is harmless — it
    covers everything compiled after the call)."""
    if not cache_dir:
        return
    import os

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # knob absent on older jax: size gating stays default
        pass
