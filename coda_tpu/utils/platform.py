"""One shared site-hook workaround for every CLI entry point.

This environment's site hook force-registers an accelerator platform and
overrides ``JAX_PLATFORMS``; when that device tunnel is wedged, any jax
array op hangs the process. Pinning must happen in-process *before any
backend initializes* — which is why every entry point defers its jax
imports and calls :func:`pin_platform` first.
"""

from __future__ import annotations

from typing import Optional


def pin_platform(platform: Optional[str]) -> None:
    """Force a jax platform (e.g. ``"cpu"``/``"tpu"``); no-op when None."""
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)
