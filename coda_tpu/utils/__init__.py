from coda_tpu.utils.checks import check_finite, check_prob

__all__ = ["check_finite", "check_prob"]
