"""Tracing / profiling utilities.

The reference has no profiling at all — only tqdm bars (SURVEY.md §5). Here
profiling is first-class and nearly free:

  * :func:`trace` wraps ``jax.profiler.trace`` so any compiled region can be
    captured to a TensorBoard/Perfetto trace directory with one flag
    (``main.py --profile-dir``);
  * :class:`StepTimer` records host-side wall-clock per labeled region and
    reports steps/sec — the per-step metrics the tracking store logs.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a device trace of the enclosed block into ``log_dir``.

    No-op when ``log_dir`` is falsy, so call sites don't branch. View with
    TensorBoard's profile plugin or Perfetto.
    """
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir, create_perfetto_trace=True):
        yield


class StepTimer:
    """Accumulates named wall-clock spans; reports totals and rates."""

    def __init__(self):
        self.spans: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def span(self, name: str, steps: int = 1):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.spans[name] = self.spans.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + steps

    def rate(self, name: str) -> float:
        """Steps/sec for a span (0.0 when never entered)."""
        dt = self.spans.get(name, 0.0)
        return self.counts.get(name, 0) / dt if dt > 0 else 0.0

    def summary(self) -> dict[str, dict]:
        return {
            k: {"seconds": self.spans[k], "steps": self.counts[k],
                "steps_per_sec": self.rate(k)}
            for k in self.spans
        }
