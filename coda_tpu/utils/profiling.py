"""Tracing / profiling utilities.

The reference has no profiling at all — only tqdm bars (SURVEY.md §5). Here
profiling is first-class and nearly free:

  * :func:`trace` wraps ``jax.profiler.trace`` so any compiled region can be
    captured to a TensorBoard/Perfetto trace directory with one flag
    (``main.py --profile-dir``);
  * :class:`StepTimer` records host-side wall-clock per labeled region and
    reports steps/sec — the per-step metrics the tracking store logs.
"""

from __future__ import annotations

import contextlib
import threading
import time


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a device trace of the enclosed block into ``log_dir``.

    No-op when ``log_dir`` is falsy, so call sites don't branch. View with
    TensorBoard's profile plugin or Perfetto.
    """
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir, create_perfetto_trace=True):
        yield


class StepTimer:
    """Accumulates named wall-clock spans; reports totals, rates, min/max.

    Thread-safe: the scheduler's async-harvest path and the serving
    batcher's tick thread can both hold one timer, so accumulation happens
    under a lock (the read-modify-write on the dicts would otherwise lose
    updates) and per-span extrema are tracked alongside the totals.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.spans: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.mins: dict[str, float] = {}
        self.maxs: dict[str, float] = {}

    @contextlib.contextmanager
    def span(self, name: str, steps: int = 1):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.spans[name] = self.spans.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + steps
                self.mins[name] = min(self.mins.get(name, dt), dt)
                self.maxs[name] = max(self.maxs.get(name, dt), dt)

    def rate(self, name: str) -> float:
        """Steps/sec for a span (0.0 when never entered)."""
        with self._lock:
            dt = self.spans.get(name, 0.0)
            return self.counts.get(name, 0) / dt if dt > 0 else 0.0

    def summary(self) -> dict[str, dict]:
        with self._lock:
            return {
                k: {"seconds": self.spans[k], "steps": self.counts[k],
                    "steps_per_sec": (self.counts[k] / self.spans[k]
                                      if self.spans[k] > 0 else 0.0),
                    "min_s": self.mins[k], "max_s": self.maxs[k]}
                for k in self.spans
            }
