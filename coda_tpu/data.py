"""Model-selection datasets: the ``(H, N, C)`` prediction tensor.

Capability parity with the reference ``Dataset`` (reference
``coda/datasets.py:4-23``): load a dense tensor of post-softmax prediction
scores — H models x N data points x C classes — plus an optional ``(N,)``
ground-truth label vector stored alongside it (``<task>_labels``).

TPU-native differences:
  * arrays are ``jax.numpy`` float32 (the reference casts to fp32 at
    ``coda/datasets.py:14`` to "avoid fp16 precision errors"; the same concern
    applies to bf16 on TPU, so fp32 is kept mandatory),
  * ``.npy``/``.npz`` are first-class formats (no torch required); ``.pt``
    files are still readable when torch is importable, for drop-in use of
    existing benchmark data,
  * a seeded synthetic task generator for tests and benchmarks, and
  * optional device placement with a ``NamedSharding`` so large tensors
    (e.g. ImageNet-scale M=500 x N=50k x C=1000 ~ 100 GB fp32) land sharded
    in HBM across the mesh instead of on one chip.
"""

from __future__ import annotations

import functools
import os
import sys
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


DATA_EXTS = (".npy", ".npz", ".pt")


def find_task_file(data_dir: str, task: str) -> Optional[str]:
    """Path of ``<data_dir>/<task>.{npy,npz,pt}``, or None."""
    for ext in DATA_EXTS:
        fp = os.path.join(data_dir, task + ext)
        if os.path.exists(fp):
            return fp
    return None


def list_tasks(data_dir: str) -> list[str]:
    """Task names with a prediction tensor under ``data_dir`` (label files
    excluded), sorted."""
    tasks = set()
    for f in os.listdir(data_dir):
        base, ext = os.path.splitext(f)
        if ext in DATA_EXTS and not base.endswith("_labels"):
            tasks.add(base)
    return sorted(tasks)


def _load_array(filepath: str) -> np.ndarray:
    """Load a dense array from .npy/.npz/.pt into host memory (numpy)."""
    if filepath.endswith(".npy"):
        return np.load(filepath)
    if filepath.endswith(".npz"):
        with np.load(filepath) as z:
            return z["preds"] if "preds" in z.files else z[z.files[0]]
    if filepath.endswith(".pt"):
        try:
            import torch
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                ".pt files require torch; convert to .npy with "
                "scripts/convert_pt.py or install torch"
            ) from e
        t = torch.load(filepath, map_location="cpu", weights_only=True)
        return t.detach().cpu().numpy()
    raise ValueError(f"Unsupported dataset file format: {filepath}")


def _labels_path(filepath: str) -> str:
    root, ext = os.path.splitext(filepath)
    return f"{root}_labels{ext}"


@dataclass
class Dataset:
    """A model-selection dataset.

    Attributes:
      preds: ``(H, N, C)`` float32 post-softmax scores.
      labels: optional ``(N,)`` int32 ground-truth classes.
      name: task name (used as the tracking experiment name).
      filenames: optional ``(N,)`` source-image filenames (written by the
        pool builder; lets the demo serve the item being labeled).
      class_names: optional ``(C,)`` human-readable class names.
    """

    preds: jax.Array
    labels: Optional[jax.Array] = None
    name: str = "task"
    filenames: Optional[list] = None
    class_names: Optional[list] = None

    @property
    def shape(self) -> tuple[int, int, int]:
        return tuple(self.preds.shape)  # (H, N, C)

    @classmethod
    def from_file(
        cls,
        filepath: str,
        sharding: Optional[jax.sharding.Sharding] = None,
        name: Optional[str] = None,
        unsharded_fallback: bool = False,
    ) -> "Dataset":
        """Load ``<task>.{npy,npz,pt}`` (+ optional ``<task>_labels.*``).

        If ``sharding`` is given the prediction tensor is placed with it
        (sharded across the mesh) instead of committed to the default
        device; see :func:`_place_preds` for ``unsharded_fallback``.
        """
        preds_np = _load_array(filepath).astype(np.float32)  # fp32 mandatory
        if preds_np.ndim != 3:
            raise ValueError(f"preds must be (H, N, C); got {preds_np.shape}")
        task = name or os.path.splitext(os.path.basename(filepath))[0]
        preds = _place_preds(preds_np, sharding, unsharded_fallback, task)

        labels = None
        filenames = class_names = None
        if filepath.endswith(".npz"):
            # single-file native format: preds + labels (+ optional item
            # filenames and class names) in one npz, as the pool builder
            # writes it
            with np.load(filepath) as z:
                if "labels" in z.files:
                    labels = jnp.asarray(z["labels"].astype(np.int32))
                if "filenames" in z.files:
                    filenames = [str(s) for s in z["filenames"]]
                if "classes" in z.files:
                    class_names = [str(s) for s in z["classes"]]
        if labels is None:
            lp = _labels_path(filepath)
            if os.path.exists(lp):
                labels = jnp.asarray(_load_array(lp).astype(np.int32))
        return cls(preds=preds, labels=labels, name=task,
                   filenames=filenames, class_names=class_names)


def _place_preds(preds_np, sharding, unsharded_fallback, name, warn=None):
    """Device placement of a host ``(H, N, C)`` array.

    With a ``sharding``, ``device_put`` goes straight from host memory into
    the shards (staging through ``jnp.asarray`` first would commit the FULL
    tensor to one chip's HBM — an OOM for exactly the over-HBM tensors
    sharding exists to serve). A ``NamedSharding`` needs even shards; with
    ``unsharded_fallback`` a shape that doesn't divide the mesh degrades to
    unsharded placement with a warning (so a heterogeneous sweep doesn't
    abort on one awkward N) instead of raising. The warning goes to stderr:
    suite runners emit machine-readable JSON on stdout.
    """
    if sharding is None:
        return jnp.asarray(preds_np)
    try:
        return jax.device_put(preds_np, sharding)
    except ValueError as e:
        # a ValueError from device_put of a host array IS a placement
        # failure (uneven shards, mesh/shape mismatch) — no error-string
        # matching needed
        if not unsharded_fallback:
            raise
        if warn is None:
            # resolve sys.stderr at call time so redirect_stderr/capsys see it
            warn = functools.partial(print, file=sys.stderr)
        warn(f"[data] {name}: sharded placement failed ({e}); "
             "loading unsharded")
        return jnp.asarray(preds_np)


def make_synthetic_task(
    seed: int,
    H: int = 8,
    N: int = 200,
    C: int = 4,
    acc_lo: float = 0.35,
    acc_hi: float = 0.9,
    sharpness: float = 4.0,
    name: Optional[str] = None,
    sharding: Optional[jax.sharding.Sharding] = None,
    unsharded_fallback: bool = False,
) -> Dataset:
    """Seeded synthetic model-selection task.

    Models span a range of true accuracies in ``[acc_lo, acc_hi]``; each
    model's per-point prediction is a peaked softmax distribution over C
    classes whose argmax equals the true label with that model's accuracy.
    Built with numpy (host) so tests/benches don't pay a device round-trip
    and traces are reproducible independent of the JAX backend.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, C, size=N).astype(np.int32)
    accs = np.linspace(acc_lo, acc_hi, H)
    # shuffle so the best model isn't always index H-1
    rng.shuffle(accs)

    logits = rng.normal(0.0, 1.0, size=(H, N, C)).astype(np.float32)
    correct = rng.random((H, N)) < accs[:, None]
    # wrong predicted class: shift true label by a random non-zero offset
    offsets = rng.integers(1, C, size=(H, N))
    wrong_cls = (labels[None, :] + offsets) % C
    pred_cls = np.where(correct, labels[None, :], wrong_cls)
    idx_h, idx_n = np.meshgrid(np.arange(H), np.arange(N), indexing="ij")
    logits[idx_h, idx_n, pred_cls] += sharpness
    # softmax
    logits -= logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(-1, keepdims=True)

    p = p.astype(np.float32)
    task = name or f"synthetic_h{H}_n{N}_c{C}_s{seed}"
    return Dataset(
        preds=_place_preds(p, sharding, unsharded_fallback, task),
        labels=jnp.asarray(labels),
        name=task,
    )
