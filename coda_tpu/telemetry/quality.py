"""Decision-quality observability: calibration, drift, and shadow audits.

PR 19's observability plane watches whether the fleet is *fast and alive*;
nothing watched whether its *decisions* are statistically healthy — a
miscalibrated P(best), a drifting surrogate residual, or a stale prior
pool serves perfectly fast, perfectly wrong answers. This module is the
decision-quality plane, three organs behind one facade:

  * :class:`CalibrationMonitor` — O(1) streaming reliability buckets over
    the flight recorder's per-round evidence: the probability the
    session's consensus posterior ``pi_hat`` assigned to the realized
    oracle label (the new additive-optional ``pred_label_prob`` row
    field), its argmax-hit indicator, and the P(best) digest. Yields
    ECE / Brier per (task, bucket) online — the amortized-gate and
    surrogate rungs get a live calibration curve, not just the 2.34e-4
    static bound. :func:`pbest_calibration` is the ground-truth variant
    for suite/bench records (P(best)-vs-realized-best).
  * :class:`CusumDetector` / :class:`PageHinkley` (+ :class:`DriftBank`)
    — one-sided drift state machines with injectable clocks over the
    surrogate's audit-gate pressure, the prior pool's staleness-regret
    estimate (the exact sensor the ROADMAP's learned-decay item needs),
    and the crowd reliability posterior's movement.
  * :class:`ShadowAuditor` — re-replays a sampled fraction of closing
    sessions' recorder streams through a scratch slab slot OFF the
    batcher thread, verifying every round bitwise with the existing
    replay machinery (``serve/recovery.py``). A clean fleet holds 0
    divergences; a single-ulp stream tamper (the ``stream_tamper``
    fault site) is caught and attributed to the exact session + round.
    For pool-seeded sessions it additionally measures the seeded-vs-cold
    warmup gap — the other half of the staleness-regret sensor.

:class:`QualityPlane` bundles the three for the serving layer (the
``--no-quality`` flag disables it wholesale), publishes lint-clean
``quality_*`` families on ``/metrics``, the ``GET /fleet/quality``
scorecard, and tracking-store snapshots, and :func:`quality_slos` registers
calibration/divergence/drift objectives into the existing
:class:`~coda_tpu.telemetry.slo.SloSweeper` burn-rate machinery.

Contract (same as tracing): quality on-vs-off leaves decision rows
bitwise identical — the plane only READS posterior state (the consensus
``pi_hat`` is computed from a pre-dispatch ``pbest`` read plus the task's
prediction tensor) and replays scratch slots that no live session owns.
``scripts/bench_quality.py`` captures the evidence; ``check_perf.py``
gates it (overhead ≤ 5%, 0 clean-fleet divergences, tamper attributed).
"""

from __future__ import annotations

import collections
import hashlib
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

__all__ = [
    "CalibrationBuckets",
    "CalibrationMonitor",
    "CusumDetector",
    "DriftBank",
    "PageHinkley",
    "QualityPlane",
    "ShadowAuditor",
    "pbest_calibration",
    "quality_slos",
    "reliability_curve",
    "tamper_rows_ulp",
]

#: reliability-diagram resolution: 10 equal-width confidence bins is the
#: standard ECE binning (Guo et al.) and keeps the accumulators O(1)
N_CALIBRATION_BINS = 10

#: a calibration verdict below this many labeled rounds is noise, not
#: evidence — snapshots report the ECE but SLO probes treat it as no-data
CALIBRATION_MIN_SAMPLES = 50


# ---------------------------------------------------------------------------
# streaming calibration
# ---------------------------------------------------------------------------

class CalibrationBuckets:
    """O(1) reliability accumulators for one (task, channel) stream.

    Per observation: the model's confidence (probability it put on its
    own argmax), whether that argmax was realized (``hit``), and
    optionally the probability assigned to the realized label itself.
    Everything downstream (ECE, Brier, the reliability curve) is a pure
    read of the per-bin sums — no per-round lists, so a million-round
    session costs the same 3 small arrays."""

    def __init__(self, bins: int = N_CALIBRATION_BINS):
        self.bins = int(bins)
        self.n = np.zeros(self.bins, np.int64)
        self.conf_sum = np.zeros(self.bins, np.float64)
        self.hit_sum = np.zeros(self.bins, np.float64)
        self.brier_sum = 0.0
        self.p_label_sum = 0.0
        self.p_label_n = 0

    def observe(self, conf: float, hit: bool,
                p_label: Optional[float] = None) -> None:
        conf = float(min(1.0, max(0.0, conf)))
        b = min(self.bins - 1, int(conf * self.bins))
        self.n[b] += 1
        self.conf_sum[b] += conf
        self.hit_sum[b] += 1.0 if hit else 0.0
        self.brier_sum += (conf - (1.0 if hit else 0.0)) ** 2
        if p_label is not None:
            self.p_label_sum += float(p_label)
            self.p_label_n += 1

    @property
    def total(self) -> int:
        return int(self.n.sum())

    def ece(self) -> Optional[float]:
        """Expected calibration error: Σ_b (n_b/n)·|acc_b − conf_b|."""
        n = self.total
        if n == 0:
            return None
        live = self.n > 0
        acc = self.hit_sum[live] / self.n[live]
        conf = self.conf_sum[live] / self.n[live]
        return float(np.sum(self.n[live] * np.abs(acc - conf)) / n)

    def brier(self) -> Optional[float]:
        n = self.total
        return None if n == 0 else self.brier_sum / n

    def snapshot(self) -> dict:
        n = self.total
        out = {
            "n": n,
            "ece": self.ece(),
            "brier": self.brier(),
            "mean_pred_label_prob": (self.p_label_sum / self.p_label_n
                                     if self.p_label_n else None),
            "bins": [],
        }
        for b in range(self.bins):
            nb = int(self.n[b])
            out["bins"].append({
                "lo": b / self.bins, "hi": (b + 1) / self.bins, "n": nb,
                "confidence": (self.conf_sum[b] / nb) if nb else None,
                "accuracy": (self.hit_sum[b] / nb) if nb else None,
            })
        return out


class CalibrationMonitor:
    """Thread-safe per-task calibration accumulators (batcher thread
    writes, HTTP workers read)."""

    def __init__(self, bins: int = N_CALIBRATION_BINS):
        self.bins = bins
        self._lock = threading.Lock()
        self._tasks: dict[str, CalibrationBuckets] = {}

    def observe(self, task: str, conf: float, hit: bool,
                p_label: Optional[float] = None) -> None:
        with self._lock:
            bk = self._tasks.get(task)
            if bk is None:
                bk = self._tasks[task] = CalibrationBuckets(self.bins)
            bk.observe(conf, hit, p_label)

    def snapshot(self) -> dict:
        with self._lock:
            return {task: bk.snapshot()
                    for task, bk in sorted(self._tasks.items())}

    def worst_ece(self, min_samples: int = CALIBRATION_MIN_SAMPLES
                  ) -> Optional[float]:
        """The worst per-task ECE among tasks with enough evidence, or
        None when no task has any (the SLO probe's no-data case)."""
        worst = None
        with self._lock:
            for bk in self._tasks.values():
                if bk.total < min_samples:
                    continue
                e = bk.ece()
                if e is not None:
                    worst = e if worst is None else max(worst, e)
        return worst


def reliability_curve(conf, hit, bins: int = N_CALIBRATION_BINS) -> dict:
    """One-shot calibration verdict over paired arrays (offline twin of
    the streaming monitor — bench/suite calls it on ground-truth runs)."""
    bk = CalibrationBuckets(bins)
    for c, h in zip(np.asarray(conf, np.float64).ravel(),
                    np.asarray(hit).ravel()):
        bk.observe(float(c), bool(h))
    return bk.snapshot()


def pbest_calibration(pbest_max, regret, bins: int = N_CALIBRATION_BINS
                      ) -> dict:
    """P(best)-vs-realized-best calibration for ground-truth runs.

    ``pbest_max`` is the per-round posterior mass on the current argmax
    model; the argmax *was* (one of) the realized best models exactly
    when that round's ``regret`` is 0 — both arrays ride every flight
    record (``engine/replay.record_calibration`` adapts a
    :class:`~coda_tpu.telemetry.recorder.RunRecord` onto this)."""
    conf = np.asarray(pbest_max, np.float64).ravel()
    hit = np.asarray(regret, np.float64).ravel() <= 0.0
    keep = np.isfinite(conf)
    return reliability_curve(conf[keep], hit[keep], bins)


# ---------------------------------------------------------------------------
# drift detectors
# ---------------------------------------------------------------------------

class CusumDetector:
    """One-sided CUSUM over a scalar stream: ``s ← max(0, s + x − μ0 − k)``,
    fire at ``s ≥ h``, clear once the statistic drains back to ``≤ clear``
    (in-control samples shrink it by ``μ0 + k − x`` each). Injectable
    clock so tests drive fire/clear without sleeping."""

    def __init__(self, name: str, mu0: float, k: float, h: float,
                 clear: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.mu0 = float(mu0)
        self.k = float(k)
        self.h = float(h)
        self.clear = float(clear)
        self._clock = clock
        self.s = 0.0
        self.firing = False
        self.fired_total = 0
        self.cleared_total = 0
        self.observations = 0
        self.last_value: Optional[float] = None
        self.last_transition_t: Optional[float] = None

    def observe(self, x: float, t: Optional[float] = None) -> Optional[str]:
        """Feed one sample; returns ``"fired"`` / ``"cleared"`` on a
        transition, else None."""
        t = self._clock() if t is None else float(t)
        self.observations += 1
        self.last_value = float(x)
        self.s = max(0.0, self.s + float(x) - self.mu0 - self.k)
        if not self.firing and self.s >= self.h:
            self.firing = True
            self.fired_total += 1
            self.last_transition_t = t
            return "fired"
        if self.firing and self.s <= self.clear:
            self.firing = False
            self.cleared_total += 1
            self.last_transition_t = t
            return "cleared"
        return None

    def snapshot(self) -> dict:
        return {"kind": "cusum", "statistic": self.s, "firing": self.firing,
                "fired_total": self.fired_total,
                "cleared_total": self.cleared_total,
                "observations": self.observations,
                "last_value": self.last_value,
                "mu0": self.mu0, "k": self.k, "h": self.h}


class PageHinkley:
    """Page-Hinkley mean-shift test: ``m ← m + x − x̄ − δ``; fire when
    ``m − min(m) > λ``; clearing resets the statistic (the classic PH has
    no clear — after a confirmed shift the new regime is the baseline)."""

    def __init__(self, name: str, delta: float, lam: float,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.delta = float(delta)
        self.lam = float(lam)
        self._clock = clock
        self.mean = 0.0
        self.m = 0.0
        self.m_min = 0.0
        self.firing = False
        self.fired_total = 0
        self.cleared_total = 0
        self.observations = 0
        self.last_value: Optional[float] = None
        self.last_transition_t: Optional[float] = None

    def observe(self, x: float, t: Optional[float] = None) -> Optional[str]:
        t = self._clock() if t is None else float(t)
        x = float(x)
        self.observations += 1
        self.last_value = x
        self.mean += (x - self.mean) / self.observations
        self.m += x - self.mean - self.delta
        self.m_min = min(self.m_min, self.m)
        ph = self.m - self.m_min
        if not self.firing and ph > self.lam:
            self.firing = True
            self.fired_total += 1
            self.last_transition_t = t
            return "fired"
        if self.firing and ph <= self.lam * 0.5:
            # the shifted stream settled (or reverted): re-baseline so the
            # detector arms for the NEXT shift instead of latching forever
            self.firing = False
            self.cleared_total += 1
            self.last_transition_t = t
            self.mean = x
            self.m = self.m_min = 0.0
            self.observations = 1
            return "cleared"
        return None

    def snapshot(self) -> dict:
        return {"kind": "page_hinkley", "statistic": self.m - self.m_min,
                "firing": self.firing, "fired_total": self.fired_total,
                "cleared_total": self.cleared_total,
                "observations": self.observations,
                "last_value": self.last_value,
                "delta": self.delta, "lambda": self.lam}


class DriftBank:
    """A named set of drift detectors behind one thread-safe feed."""

    def __init__(self, detectors=()):
        self._lock = threading.Lock()
        self._detectors = {d.name: d for d in detectors}

    def add(self, detector) -> None:
        with self._lock:
            self._detectors[detector.name] = detector

    def observe(self, name: str, x: float,
                t: Optional[float] = None) -> Optional[str]:
        with self._lock:
            d = self._detectors.get(name)
            if d is None:
                return None
            return d.observe(x, t)

    def any_firing(self) -> bool:
        with self._lock:
            return any(d.firing for d in self._detectors.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {name: d.snapshot()
                    for name, d in sorted(self._detectors.items())}


def default_drift_bank(clock: Callable[[], float] = time.monotonic
                       ) -> DriftBank:
    """The serve plane's stock detectors, one per approximation contract:

    * ``surrogate_residual`` — CUSUM over the live gate-pressure signal
      (:func:`~coda_tpu.selectors.surrogate.gate_pressure`): healthy
      fits hold pressure near 0; sustained pressure toward 1 means the
      audit-set residual is eating the 2.34e-4 contract.
    * ``prior_staleness`` — CUSUM over the pool's staleness-regret
      estimate (gate rejections per credited warmup round, fused with
      the auditor's seeded-vs-cold gap): the learned-decay sensor.
    * ``crowd_reliability`` — Page-Hinkley over the annotator posterior's
      accuracy movement (:func:`~coda_tpu.crowd.reliability
      .accuracy_movement`): a sustained shift means the crowd changed
      under the fleet.
    """
    return DriftBank([
        CusumDetector("surrogate_residual", mu0=0.1, k=0.05, h=2.0,
                      clear=0.5, clock=clock),
        CusumDetector("prior_staleness", mu0=0.05, k=0.05, h=1.5,
                      clear=0.25, clock=clock),
        PageHinkley("crowd_reliability", delta=0.005, lam=0.25,
                    clock=clock),
    ])


# ---------------------------------------------------------------------------
# shadow auditor
# ---------------------------------------------------------------------------

def tamper_rows_ulp(rows: list, round_i: Optional[int] = None) -> list:
    """Flip ONE float quantity of one decision row by a single ulp — the
    smallest representable stream corruption, the tamper the auditor must
    still catch (bitwise replay admits nothing less). Returns a deep-ish
    copy; the caller's rows are untouched."""
    rows = [dict(r) for r in rows]
    if not rows:
        return rows
    i = len(rows) // 2 if round_i is None else int(round_i)
    i = min(max(i, 0), len(rows) - 1)
    row = rows[i]
    for q in ("next_prob", "pbest_max", "pbest_entropy"):
        v = row.get(q)
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            v2 = list(v)
            v2[0] = float(np.nextafter(np.float32(v2[0]), np.float32(np.inf)))
            row[q] = v2
        else:
            row[q] = float(np.nextafter(np.float32(v), np.float32(np.inf)))
        return rows
    # all-None digests (method without get_pbest): flip the int pick
    row["next_idx"] = (int(row["next_idx"]) + 1
                       if not isinstance(row["next_idx"], list)
                       else [int(row["next_idx"][0]) + 1]
                       + [int(v) for v in row["next_idx"][1:]])
    return rows


class ShadowAuditor:
    """Bitwise re-replay of sampled session streams through scratch slots.

    Reuses the recovery machinery verbatim — ``stage_fresh`` +
    per-round ``dispatch`` + ``check_row`` — so the auditor's verdict IS
    the restore/import contract, continuously enforced in production.
    Replay runs on the caller's (worker) thread; each round takes the
    bucket's dispatch lock like any label request, so live sessions are
    never perturbed (masked dispatch touches only the scratch slot's
    state/key rows).

    ``faults`` (optional :class:`~coda_tpu.serve.faults.FaultInjector`)
    arms the ``stream_tamper`` site: when it fires, the auditor's
    in-memory copy of the rows is ulp-tampered BEFORE replay — the
    end-to-end detection drill the bench runs (the session's real stream
    is untouched)."""

    def __init__(self, faults=None, registry=None, recent_s: float = 600.0,
                 clock: Callable[[], float] = time.monotonic,
                 measure_prior_gap: bool = True):
        self.faults = faults
        self.registry = registry
        self.recent_s = float(recent_s)
        self._clock = clock
        self.measure_prior_gap = measure_prior_gap
        self._lock = threading.Lock()
        self.audits_total = 0
        self.audits_skipped = 0      # SlabFull / empty stream / quarantine
        self.rounds_verified = 0
        self.divergences_total = 0
        self.tampered_total = 0      # audits whose rows the fault corrupted
        # (t, {"session", "round", "detail"}) — recent() drives the SLO
        # probe, the bounded deque keeps the /fleet/quality evidence
        self._divergences: collections.deque = collections.deque(maxlen=256)
        # seeded-vs-cold warmup gap EWMA over audited pool-seeded sessions
        self.prior_gap: Optional[float] = None
        self.prior_gap_sessions = 0

    # -- verdict plumbing --------------------------------------------------
    def _record_divergence(self, sid: str, round_i: Optional[int],
                           detail: str) -> None:
        t = self._clock()
        with self._lock:
            self.divergences_total += 1
            self._divergences.append(
                (t, {"session": sid, "round": round_i, "detail": detail}))
        if self.registry is not None:
            # distinct name from the snapshot-driven exposition family
            # (quality_audit_divergences_total) — the registry copy rides
            # the telemetry.json shutdown artifact
            self.registry.counter(
                "quality_shadow_divergences_total",
                "Shadow-audit replays that diverged bitwise from their "
                "recorded stream").inc()

    def recent_divergences(self, now: Optional[float] = None) -> int:
        now = self._clock() if now is None else float(now)
        cutoff = now - self.recent_s
        with self._lock:
            return sum(1 for t, _ in self._divergences if t >= cutoff)

    # -- the audit ---------------------------------------------------------
    def audit(self, bucket, sid: str, seed: int, rows,
              prior: Optional[dict] = None,
              task: Optional[str] = None) -> dict:
        """Replay one closed session's stream through a scratch slot and
        verify every round bitwise. Returns the verdict dict (also folded
        into the counters)."""
        from coda_tpu.serve.recovery import (
            ReplayMismatch,
            _request_from_row,
            check_row,
            data_rows,
        )
        from coda_tpu.serve.state import SlabFull

        rows = data_rows(rows)
        if not rows:
            with self._lock:
                self.audits_skipped += 1
            return {"session": sid, "status": "skipped", "reason": "empty"}
        tampered = False
        if self.faults is not None and "stream_tamper" in \
                self.faults.fire("audit_pre", task=task):
            rows = tamper_rows_ulp(rows)
            tampered = True
            with self._lock:
                self.tampered_total += 1
        try:
            slot = bucket.allocate(seed, prior=prior)
        except SlabFull:
            # a full slab means live traffic owns every slot — auditing is
            # strictly lower priority, skip rather than block admission
            with self._lock:
                self.audits_skipped += 1
            return {"session": sid, "status": "skipped", "reason": "full"}
        verdict: dict = {"session": sid, "status": "ok",
                         "rounds": len(rows), "tampered": tampered}
        try:
            # allocate() already staged the fresh init for (seed, prior) —
            # the same stage_fresh choreography import_session replays from
            replayed = []
            for k, row in enumerate(rows):
                with bucket.lock:
                    res = bucket.dispatch({slot: _request_from_row(row)})[slot]
                replayed.append(res)
                try:
                    check_row(row, res, k, sid=sid)
                except ReplayMismatch as e:
                    self._record_divergence(sid, k, str(e))
                    verdict.update(status="diverged", round=k, detail=str(e))
                    break
            if verdict["status"] == "ok" and prior is not None \
                    and self.measure_prior_gap:
                verdict["prior_gap"] = self._cold_gap(bucket, seed, rows,
                                                      replayed)
        except Exception as e:  # quarantine/step failure: not a divergence
            with self._lock:
                self.audits_skipped += 1
            return {"session": sid, "status": "skipped", "reason": repr(e)}
        finally:
            bucket.release(slot)
        with self._lock:
            self.audits_total += 1
            if verdict["status"] == "ok":
                self.rounds_verified += len(rows)
        if self.registry is not None:
            self.registry.counter(
                "quality_shadow_audits_total",
                "Sessions re-replayed by the shadow auditor").inc()
        return verdict

    def _cold_gap(self, bucket, seed: int, rows, seeded_results) -> float:
        """Fraction of rounds where a COLD replay (no pool prior) picks a
        different point than the recorded seeded run — the seeded-vs-cold
        warmup gap, the live estimate of what the pool prior is actually
        changing (a stale prior's gap collapses toward noise)."""
        from coda_tpu.serve.recovery import _request_from_row
        from coda_tpu.serve.state import SlabFull

        try:
            slot = bucket.allocate(seed, prior=None)
        except SlabFull:
            return self.prior_gap if self.prior_gap is not None else 0.0
        try:
            diff = 0
            for row, seeded in zip(rows, seeded_results):
                with bucket.lock:
                    res = bucket.dispatch(
                        {slot: _request_from_row(row)})[slot]
                if res["next_idx"] != seeded["next_idx"]:
                    diff += 1
        finally:
            bucket.release(slot)
        gap = diff / max(1, len(rows))
        with self._lock:
            self.prior_gap_sessions += 1
            self.prior_gap = gap if self.prior_gap is None \
                else 0.8 * self.prior_gap + 0.2 * gap
        return gap

    def snapshot(self, now: Optional[float] = None) -> dict:
        with self._lock:
            recent = list(self._divergences)[-8:]
            snap = {
                "audits_total": self.audits_total,
                "audits_skipped": self.audits_skipped,
                "rounds_verified": self.rounds_verified,
                "divergences_total": self.divergences_total,
                "tampered_total": self.tampered_total,
                "prior_gap": self.prior_gap,
                "prior_gap_sessions": self.prior_gap_sessions,
                "recent_window_s": self.recent_s,
            }
        snap["divergences_recent"] = self.recent_divergences(now)
        snap["last_divergences"] = [d for _, d in recent]
        return snap


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------

def _sample_hash(sid: str) -> float:
    """Deterministic [0, 1) coordinate of a session id — the audit
    sampling decision is a property of the sid, reproducible across
    replicas and restarts (no RNG state to carry)."""
    h = hashlib.sha1(sid.encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class QualityPlane:
    """The serving layer's decision-quality facade.

    ``preds_fn(task) -> (H, N, C) ndarray`` resolves the task's prediction
    tensor (``SessionStore.task_preds``); everything else is optional.
    The batcher calls :meth:`pre_dispatch` under the bucket lock just
    before each dispatch — a pure read (pre-update ``pbest`` + the static
    preds tensor) that computes the consensus ``pi_hat`` evidence, feeds
    the calibration monitor, and hands back the per-slot
    ``pred_label_prob`` the recorder row carries. Close-time,
    :meth:`maybe_enqueue_audit` samples sessions into the background
    audit worker."""

    def __init__(self, preds_fn=None, faults=None, registry=None,
                 audit_frac: float = 0.25, recent_s: float = 600.0,
                 clock: Callable[[], float] = time.monotonic,
                 measure_prior_gap: bool = True):
        self.preds_fn = preds_fn
        self.registry = registry
        self.audit_frac = float(audit_frac)
        self._clock = clock
        self.calibration = CalibrationMonitor()
        self.drift = default_drift_bank(clock)
        self.auditor = ShadowAuditor(faults=faults, registry=registry,
                                     recent_s=recent_s, clock=clock,
                                     measure_prior_gap=measure_prior_gap)
        self._queue: queue.Queue = queue.Queue(maxsize=256)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.audit_queue_drops = 0
        self.pre_dispatch_errors = 0
        self._lock = threading.Lock()

    # -- batcher seam ------------------------------------------------------
    def pre_dispatch(self, bucket, task: str, labeled: list) -> dict:
        """Consensus-posterior evidence for one tick's labeled requests.

        ``labeled`` is ``[(slot, idx, label), ...]`` where ``idx``/
        ``label`` are scalars or q-wide lists (the batch-label rows).
        Called UNDER the bucket's dispatch lock so the ``pbest`` read is
        the exact pre-update posterior the recorded round was decided
        under. Returns ``{slot: pred_label_prob}`` (scalar or q-wide
        list, matching the row shape); slots whose method exposes no
        posterior are absent."""
        out: dict = {}
        if not labeled:
            return out
        try:
            preds = self.preds_fn(task) if self.preds_fn else None
            if preds is None:
                return out
            # the fused read when the bucket offers it (one jitted call
            # per slot); plain pbest() keeps foreign buckets working
            read = getattr(bucket, "pbest_at", None) or bucket.pbest
            for slot, idx, label in labeled:
                pb = read(slot)
                if pb is None:
                    continue
                pb = np.asarray(pb, np.float64)
                s = pb.sum()
                if not np.isfinite(s) or s <= 0:
                    continue
                pb = pb / s
                idxs = idx if isinstance(idx, (list, tuple)) else [idx]
                labs = label if isinstance(label, (list, tuple)) else [label]
                probs = []
                for i, y in zip(idxs, labs):
                    pi = pb @ preds[:, int(i), :]        # (C,) consensus
                    z = pi.sum()
                    pi = pi / z if z > 0 else pi
                    y = int(y)
                    p_label = float(pi[y]) if 0 <= y < pi.shape[0] else 0.0
                    probs.append(p_label)
                    conf = float(pi.max())
                    self.calibration.observe(task, conf,
                                             int(np.argmax(pi)) == y,
                                             p_label)
                out[slot] = (probs if isinstance(idx, (list, tuple))
                             else probs[0])
        except Exception:
            # evidence collection must never fail a label request; the
            # counter keeps the failure visible instead of silent
            with self._lock:
                self.pre_dispatch_errors += 1
            return {}
        return out

    # -- audit sampling ----------------------------------------------------
    def should_audit(self, sid: str) -> bool:
        return _sample_hash(sid) < self.audit_frac

    def maybe_enqueue_audit(self, bucket, sid: str, seed: int, rows,
                            prior: Optional[dict] = None,
                            task: Optional[str] = None) -> bool:
        """Close-time hook: sample the session, snapshot its stream, and
        hand it to the worker thread. Never blocks (a full queue drops
        the audit and counts it)."""
        if not rows or not self.should_audit(sid):
            return False
        job = {"bucket": bucket, "sid": sid, "seed": int(seed),
               "rows": [dict(r) for r in rows], "prior": prior,
               "task": task}
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            with self._lock:
                self.audit_queue_drops += 1
            return False
        self._ensure_worker()
        return True

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._worker_loop, name="quality-audit", daemon=True)
            self._worker.start()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self.auditor.audit(job["bucket"], job["sid"], job["seed"],
                                   job["rows"], prior=job["prior"],
                                   task=job["task"])
            except Exception:
                pass  # the auditor is advisory; a crash must not recur-kill
            finally:
                self._queue.task_done()

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every queued audit ran (bench/test determinism)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._queue.unfinished_tasks == 0

    def stop(self) -> None:
        self._stop.set()

    # -- drift feed --------------------------------------------------------
    def observe_drift(self, name: str, x: float,
                      t: Optional[float] = None) -> Optional[str]:
        return self.drift.observe(name, x, t)

    def feed_serve_stats(self, buckets: list, prior_totals: dict) -> None:
        """Fold one /stats pass's live signals into the detectors:
        surrogate gate pressure (worst bucket) and the prior pool's
        live staleness-regret estimate (gate rejections per credited
        warmup round, blended with the auditor's seeded-vs-cold gap
        complement when it has evidence)."""
        from coda_tpu.selectors.surrogate import gate_pressure

        pressures = [gate_pressure(b["surrogate"].get("contract_margin"))
                     for b in buckets or ()
                     if isinstance(b.get("surrogate"), dict)]
        if pressures:
            self.observe_drift("surrogate_residual", max(pressures))
        credited = (prior_totals or {}).get("prior_warmup_rounds_skipped")
        rejects = (prior_totals or {}).get("prior_gate_rejections")
        if credited:
            regret = min(1.0, (rejects or 0) / max(1, credited))
            gap = self.auditor.prior_gap
            if gap is not None:
                # a HEALTHY prior shows a large seeded-vs-cold gap (it is
                # actually steering warmup); staleness is the complement
                regret = 0.5 * regret + 0.5 * (1.0 - gap)
            self.observe_drift("prior_staleness", regret)

    # -- reading -----------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``/stats``-embedded (and metrics-provider) payload."""
        with self._lock:
            drops = self.audit_queue_drops
            errors = self.pre_dispatch_errors
        return {
            "audit_frac": self.audit_frac,
            "calibration": self.calibration.snapshot(),
            "drift": self.drift.snapshot(),
            "audit": self.auditor.snapshot(),
            "audit_queue_drops": drops,
            "pre_dispatch_errors": errors,
        }

    def scorecard(self) -> dict:
        """The ``GET /fleet/quality`` verdict: the snapshot plus one
        summary grade per organ."""
        snap = self.snapshot()
        ece = self.calibration.worst_ece()
        audit = snap["audit"]
        snap["verdict"] = {
            "calibration": ("no_data" if ece is None
                            else ("ok" if ece <= 0.25 else "miscalibrated")),
            "worst_ece": ece,
            "audit": ("diverged" if audit["divergences_recent"] > 0
                      else ("ok" if audit["audits_total"] else "no_data")),
            "drift": "firing" if self.drift.any_firing() else "ok",
        }
        return snap

    def log_to_store(self, store, run_name: str = "quality-snapshot",
                     params: Optional[dict] = None) -> str:
        """Flush the scalar quality evidence into the MLflow-schema
        tracking store (experiment ``serve_quality``), next to the SLO
        transitions and telemetry counters."""
        snap = self.snapshot()
        with store.run("serve_quality", run_name,
                       params=params or {}) as run:
            audit = snap["audit"]
            for key in ("audits_total", "rounds_verified",
                        "divergences_total", "tampered_total"):
                run.log_metric(f"audit_{key}", float(audit[key]))
            if audit["prior_gap"] is not None:
                run.log_metric("audit_prior_gap", float(audit["prior_gap"]))
            for task, cal in snap["calibration"].items():
                if cal["ece"] is not None:
                    run.log_metric(f"ece.{task}", float(cal["ece"]))
                    run.log_metric(f"brier.{task}", float(cal["brier"]))
                run.log_metric(f"calibration_n.{task}", float(cal["n"]))
            for name, det in snap["drift"].items():
                run.log_metric(f"drift_firing.{name}",
                               1.0 if det["firing"] else 0.0)
                run.log_metric(f"drift_fired_total.{name}",
                               float(det["fired_total"]))
        return run.run_uuid


# ---------------------------------------------------------------------------
# SLO objectives
# ---------------------------------------------------------------------------

def quality_slos(max_ece: float = 0.25) -> list:
    """Quality objectives over ``SessionRouter.stats()`` snapshots, for
    registration next to :func:`~coda_tpu.telemetry.slo
    .default_fleet_slos` in the same :class:`SloSweeper`. Each replica's
    /stats embeds the plane's snapshot under ``"quality"`` (absent with
    ``--no-quality`` → the objectives report no-data, never burn)."""
    from coda_tpu.telemetry.slo import SLObjective, _replica_snaps

    def _quality_snaps(snapshot):
        return [s["quality"] for s in _replica_snaps(snapshot)
                if isinstance(s.get("quality"), dict)]

    def audit_divergence(snapshot):
        saw = None
        for q in _quality_snaps(snapshot):
            audit = q.get("audit") or {}
            if not audit.get("audits_total"):
                continue
            saw = saw or 0.0
            if (audit.get("divergences_recent") or 0) > 0:
                saw = 1.0
        return saw

    def calibration_ece(snapshot):
        saw = None
        for q in _quality_snaps(snapshot):
            for cal in (q.get("calibration") or {}).values():
                if (cal.get("n") or 0) < CALIBRATION_MIN_SAMPLES:
                    continue
                saw = saw or 0.0
                if (cal.get("ece") or 0.0) > max_ece:
                    saw = 1.0
        return saw

    def drift_firing(snapshot):
        saw = None
        for q in _quality_snaps(snapshot):
            drift = q.get("drift") or {}
            if not drift:
                continue
            saw = saw or 0.0
            if any(d.get("firing") for d in drift.values()):
                saw = 1.0
        return saw

    return [
        SLObjective("quality_audit_divergence",
                    "0 bitwise divergences from shadow-audited session "
                    "replays (recent window)", audit_divergence,
                    budget=0.001),
        SLObjective("quality_calibration_ece",
                    f"per-task streaming ECE <= {max_ece:g} once "
                    f"{CALIBRATION_MIN_SAMPLES} rounds of evidence exist",
                    calibration_ece, budget=0.01),
        SLObjective("quality_drift",
                    "no decision-quality drift detector firing "
                    "(surrogate residual / prior staleness / crowd "
                    "reliability)", drift_firing, budget=0.01),
    ]
