"""SLO watchtower: declarative objectives + multi-window burn-rate alerts.

The serve stack commits to service objectives in its artifacts (label p99
under a bound, wake p99 under one batcher tick, migrations digest-verified,
0 unaudited argmax picks) but until now nothing evaluated them *online*.
This module is the sensor plane the future autoscaler subscribes to:

  * :class:`SLObjective` — one declarative objective: a ``probe`` mapping
    the router's aggregated fleet snapshot (``SessionRouter.stats()``) to a
    *bad fraction* in [0, 1] (or ``None`` when the underlying family has no
    data yet), plus the long-run error ``budget`` the burn rate is
    normalized against.
  * :class:`SloSweeper` — evaluates every objective on each observation,
    maintains fast/slow rolling windows (Google SRE multi-window
    multi-burn-rate: default 5 m / 1 h), and runs the alert state machine:
    **fire** when BOTH windows burn above ``fire_threshold`` (the fast
    window makes the alert responsive, the slow window makes it ignore
    blips), **clear** when the fast window burns below ``clear_threshold``
    (hysteresis — a freshly-fired alert does not flap while the slow
    window drains). Typed alert events are retained, mirrored into
    ``coda_slo_*`` registry families (rendered lint-clean by
    ``render_fleet``), and flushed to the MLflow-schema tracking store.

Burn rate = (windowed mean bad fraction) / budget: 1.0 burns the error
budget exactly at the sustainable rate; the default fire threshold of 8
corresponds to a fast, page-worthy burn. Time comes from an injectable
monotonic clock (``time.monotonic``) so unit tests drive synthetic streams
across the windows without sleeping.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "SLObjective",
    "SloSweeper",
    "default_fleet_slos",
]


@dataclass(frozen=True)
class SLObjective:
    """One declarative objective over the aggregated fleet snapshot."""

    name: str
    description: str
    #: fleet snapshot -> bad fraction in [0, 1]; None = no data (objective
    #: reports ``no_data`` and never burns)
    probe: Callable[[dict], Optional[float]]
    #: long-run allowed bad fraction (burn rate 1.0 == spending exactly this)
    budget: float = 0.01


class _Window:
    """Rolling (t, bad) samples over a fixed horizon; O(1) amortized."""

    def __init__(self, horizon_s: float):
        self.horizon_s = horizon_s
        self._samples: collections.deque = collections.deque()

    def add(self, t: float, bad: float) -> None:
        self._samples.append((t, bad))
        cutoff = t - self.horizon_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def mean(self) -> Optional[float]:
        if not self._samples:
            return None
        return sum(b for _, b in self._samples) / len(self._samples)

    def __len__(self) -> int:
        return len(self._samples)


class _ObjectiveState:
    def __init__(self, obj: SLObjective, fast_s: float, slow_s: float):
        self.obj = obj
        self.fast = _Window(fast_s)
        self.slow = _Window(slow_s)
        self.firing = False
        self.fired_total = 0
        self.cleared_total = 0
        self.last_bad: Optional[float] = None
        self.burn_fast: Optional[float] = None
        self.burn_slow: Optional[float] = None


class SloSweeper:
    """Evaluate objectives on fleet snapshots; fire/clear burn-rate alerts.

    Thread-safe: the router's poll thread calls :meth:`observe` while HTTP
    handlers read :meth:`snapshot`. ``registry`` (optional) receives the
    ``slo_*`` gauge/counter families; ``store`` (optional, MLflow-schema
    :class:`~coda_tpu.tracking.store.TrackingStore`-like, or a zero-arg
    factory returning one — resolved lazily on the flushing thread because
    sqlite connections are thread-bound) receives one run per alert
    transition under the ``serve_slo`` experiment.
    """

    def __init__(self, objectives: list[SLObjective],
                 registry=None, store=None,
                 fast_s: float = 300.0, slow_s: float = 3600.0,
                 fire_threshold: float = 8.0, clear_threshold: float = 1.0,
                 min_samples: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        if clear_threshold > fire_threshold:
            raise ValueError("clear_threshold must not exceed fire_threshold")
        self.objectives = list(objectives)
        self.registry = registry
        self.store = store
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.fire_threshold = float(fire_threshold)
        self.clear_threshold = float(clear_threshold)
        self.min_samples = int(min_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._states = {o.name: _ObjectiveState(o, self.fast_s, self.slow_s)
                        for o in self.objectives}
        self.observations = 0
        # every fire/clear transition ever (bounded: alerts are rare by
        # construction; the deque guards against a flapping objective)
        self.alerts: collections.deque = collections.deque(maxlen=1024)
        self._store_flushed = 0
        self._store_errors = 0

    # -- evaluation --------------------------------------------------------
    def observe(self, snapshot: dict, t: Optional[float] = None) -> list:
        """Evaluate every objective against one fleet snapshot.

        Returns the alert transitions produced by THIS observation (also
        retained in :attr:`alerts` and flushed to the store)."""
        t = self._clock() if t is None else float(t)
        transitions = []
        with self._lock:
            self.observations += 1
            for st in self._states.values():
                try:
                    bad = st.obj.probe(snapshot)
                except Exception:
                    bad = None  # a broken probe must not kill the sweeper
                st.last_bad = bad
                if bad is None:
                    continue
                bad = min(1.0, max(0.0, float(bad)))
                st.fast.add(t, bad)
                st.slow.add(t, bad)
                budget = max(st.obj.budget, 1e-12)
                fmean, smean = st.fast.mean(), st.slow.mean()
                st.burn_fast = None if fmean is None else fmean / budget
                st.burn_slow = None if smean is None else smean / budget
                if len(st.fast) < self.min_samples:
                    continue
                ev = self._step_alert(st, t)
                if ev is not None:
                    transitions.append(ev)
        for ev in transitions:
            self._flush_alert(ev)
        self._export_registry()
        return transitions

    def _step_alert(self, st: _ObjectiveState, t: float) -> Optional[dict]:
        """Fire/clear state machine for one objective (lock held)."""
        bf = st.burn_fast if st.burn_fast is not None else 0.0
        bs = st.burn_slow if st.burn_slow is not None else 0.0
        ev = None
        if not st.firing and bf >= self.fire_threshold \
                and bs >= self.fire_threshold:
            st.firing = True
            st.fired_total += 1
            ev = self._alert(st, "firing", t)
        elif st.firing and bf < self.clear_threshold:
            st.firing = False
            st.cleared_total += 1
            ev = self._alert(st, "resolved", t)
        if ev is not None:
            self.alerts.append(ev)
        return ev

    def _alert(self, st: _ObjectiveState, state: str, t: float) -> dict:
        return {
            "slo": st.obj.name,
            "state": state,
            "burn_fast": st.burn_fast,
            "burn_slow": st.burn_slow,
            "budget": st.obj.budget,
            "t_monotonic": t,
            "seq": st.fired_total + st.cleared_total,
        }

    # -- export ------------------------------------------------------------
    def _export_registry(self) -> None:
        if self.registry is None:
            return
        reg = self.registry
        burn_f = reg.gauge("slo_burn_rate_fast",
                           "Fast-window burn rate per objective "
                           "(windowed bad fraction / budget)")
        burn_s = reg.gauge("slo_burn_rate_slow",
                           "Slow-window burn rate per objective")
        bad = reg.gauge("slo_bad_fraction",
                        "Instantaneous bad fraction per objective")
        firing = reg.gauge("slo_firing",
                           "1 while the objective's burn-rate alert fires")
        with self._lock:
            for st in self._states.values():
                name = st.obj.name
                if st.burn_fast is not None:
                    burn_f.set(st.burn_fast, slo=name)
                if st.burn_slow is not None:
                    burn_s.set(st.burn_slow, slo=name)
                if st.last_bad is not None:
                    bad.set(st.last_bad, slo=name)
                firing.set(1.0 if st.firing else 0.0, slo=name)

    def _flush_alert(self, ev: dict) -> None:
        """One tracking-store run per alert transition (typed event)."""
        # registry counter ALWAYS steps, store flush is best-effort
        if self.registry is not None:
            self.registry.counter(
                "slo_alerts_total",
                "Burn-rate alert transitions by objective and state").inc(
                    1.0, slo=ev["slo"], state=ev["state"])
        if self.store is None:
            return
        try:
            if not hasattr(self.store, "run"):
                # zero-arg factory: the TrackingStore's sqlite connection is
                # bound to its creating thread, and alerts flush from the
                # router's poll thread — so the store must be BORN here, not
                # on whatever thread built the sweeper
                self.store = self.store()
            with self.store.run(
                    "serve_slo", f"alert-{ev['slo']}-{ev['state']}",
                    params={"slo": ev["slo"], "state": ev["state"],
                            "budget": str(ev["budget"])}) as run:
                run.log_metric("burn_fast", float(ev["burn_fast"] or 0.0))
                run.log_metric("burn_slow", float(ev["burn_slow"] or 0.0))
                run.log_metric("firing",
                               1.0 if ev["state"] == "firing" else 0.0)
            self._store_flushed += 1
        except Exception:
            self._store_errors += 1  # alerting must survive a broken store

    # -- reading -----------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``GET /fleet/slo`` payload."""
        with self._lock:
            objectives = {}
            for st in self._states.values():
                objectives[st.obj.name] = {
                    "description": st.obj.description,
                    "budget": st.obj.budget,
                    "bad_fraction": st.last_bad,
                    "no_data": st.last_bad is None,
                    "burn_fast": st.burn_fast,
                    "burn_slow": st.burn_slow,
                    "firing": st.firing,
                    "fired_total": st.fired_total,
                    "cleared_total": st.cleared_total,
                    "window_samples": [len(st.fast), len(st.slow)],
                }
            return {
                "windows_s": {"fast": self.fast_s, "slow": self.slow_s},
                "thresholds": {"fire": self.fire_threshold,
                               "clear": self.clear_threshold},
                "observations": self.observations,
                "objectives": objectives,
                "alerts": list(self.alerts)[-64:],
                "alerts_total": len(self.alerts),
                "store": {"flushed": self._store_flushed,
                          "errors": self._store_errors},
            }


# -- default objective set ---------------------------------------------------

def _agg(snapshot: dict) -> dict:
    return snapshot.get("aggregate") or {}


def _router(snapshot: dict) -> dict:
    return snapshot.get("router") or {}


def _replica_snaps(snapshot: dict) -> list[dict]:
    reps = snapshot.get("replicas") or {}
    return [s for s in reps.values()
            if isinstance(s, dict) and "error" not in s]


def _max_p99_ms(snapshot: dict, ring: str) -> Optional[float]:
    """Worst per-replica p99 of one latency ring, ms (None = no data)."""
    worst = None
    for snap in _replica_snaps(snapshot):
        summ = snap.get(ring) or {}
        p99 = summ.get("p99_ms")
        if p99 is None:
            continue
        worst = p99 if worst is None else max(worst, p99)
    return worst


def default_fleet_slos(label_p99_ms: float = 250.0,
                       wake_p99_ms: float = 50.0) -> list[SLObjective]:
    """The committed objective set from the fleet artifacts, as probes over
    ``SessionRouter.stats()``. Bounds are deployment knobs: ``wake_p99_ms``
    should be one batcher tick (`max_wait_ms` + dispatch)."""

    def label_p99(snapshot):
        p99 = _max_p99_ms(snapshot, "request_latency")
        return None if p99 is None else (1.0 if p99 > label_p99_ms else 0.0)

    def error_ratio(snapshot):
        agg = _agg(snapshot)
        total = agg.get("requests") or 0
        if not total:
            return None
        bad = (agg.get("requests_rejected") or 0) + \
            (agg.get("requests_failed") or 0)
        return min(1.0, bad / total)

    def wake_p99(snapshot):
        p99 = _max_p99_ms(snapshot, "wake_latency")
        return None if p99 is None else (1.0 if p99 > wake_p99_ms else 0.0)

    def warm_misses(snapshot):
        # post-start contract: a warm-pool MISS after the pool is primed
        # (size > 0) means a shape fell out of the AOT cache — a recompile
        # in the hot path
        saw = None
        for snap in _replica_snaps(snapshot):
            wp = snap.get("warm_pool") or {}
            if not (wp.get("size") or 0):
                continue
            saw = saw or 0.0
            if (wp.get("misses") or 0) > 0:
                saw = 1.0
        return saw

    def unaudited_argmax(snapshot):
        # the surrogate trust gate makes unaudited picks structurally 0
        # (escape/audit-rank/score-contract all force an exact fallback);
        # the probe watches the counter so a gate regression burns
        # immediately. No surrogate bucket anywhere -> no data.
        saw = None
        for snap in _replica_snaps(snapshot):
            if "surrogate_rounds" not in snap:
                continue
            saw = saw or 0.0
            if (snap.get("surrogate_unaudited_picks") or 0) > 0:
                saw = 1.0
        return saw

    def migrations_verified(snapshot):
        r = _router(snapshot)
        migrations = (r.get("counters") or {}).get("migrations")
        if migrations is None:
            migrations = r.get("migrations")
        if not migrations:
            return None
        verified = r.get("migration_verified") or 0
        return 0.0 if verified >= migrations else 1.0

    return [
        SLObjective("label_p99",
                    f"label request p99 <= {label_p99_ms:g} ms "
                    "(worst replica)", label_p99, budget=0.05),
        SLObjective("error_ratio",
                    "rejected+failed requests / total requests",
                    error_ratio, budget=0.01),
        SLObjective("wake_p99",
                    f"tier wake p99 <= {wake_p99_ms:g} ms (one batcher "
                    "tick)", wake_p99, budget=0.05),
        SLObjective("warm_pool_misses",
                    "0 warm-pool misses after the pool is primed",
                    warm_misses, budget=0.001),
        SLObjective("unaudited_argmax",
                    "0 argmax picks driven by an unaudited surrogate score",
                    unaudited_argmax, budget=0.001),
        SLObjective("migrations_verified",
                    "every migration digest-verified "
                    "(migration_verified == migrations)",
                    migrations_verified, budget=0.001),
    ]
