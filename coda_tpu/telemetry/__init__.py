"""Unified telemetry: spans, counters/gauges, Perfetto export, Prometheus.

One evidence layer for every hot loop in the stack:

  * :mod:`~coda_tpu.telemetry.spans` — thread-safe structured span recorder
    (named begin/end events on per-device + host lanes) exported as Chrome
    ``trace_event`` JSON, loadable in Perfetto / ``chrome://tracing``;
  * :mod:`~coda_tpu.telemetry.registry` — process-wide counters/gauges with
    a ``jax.monitoring``-backed jit-recompile counter and per-device HBM
    watermarks from ``device.memory_stats()``;
  * :mod:`~coda_tpu.telemetry.prometheus` — text exposition of both, served
    at ``GET /metrics`` by the serving layer and dumpable from batch runs.

:class:`Telemetry` bundles the three for the plumbing layers: every driver
(``cli.py``, ``scripts/run_suite.py``, ``scripts/bench_suite.py``, ``serve``)
grows a ``--telemetry-dir`` flag that writes ``trace.json`` +
``telemetry.json`` (+ ``metrics.prom``) artifacts there and can flush the
scalar counters into the MLflow-schema tracking store next to experiment
metrics. See ARCHITECTURE.md §"Observability".
"""

from __future__ import annotations

import atexit
import json
import os
from typing import Optional

from coda_tpu.telemetry.costs import (
    COSTS,
    CostBook,
    CostTracked,
    analyze_compiled,
    aot_call,
    harvest_executable_cost,
    roofline,
)
from coda_tpu.telemetry.prometheus import lint as lint_prometheus
from coda_tpu.telemetry.prometheus import render as render_prometheus
from coda_tpu.telemetry.quality import (
    CalibrationMonitor,
    CusumDetector,
    DriftBank,
    PageHinkley,
    QualityPlane,
    ShadowAuditor,
    pbest_calibration,
    quality_slos,
    reliability_curve,
)
from coda_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Registry,
    get_registry,
    install_jax_hooks,
    jax_hooks_installed,
    registry_hooked,
    sample_device_memory,
)
from coda_tpu.telemetry.recorder import (
    CROSS_BACKEND_SCORE_TOL,
    RECORD_SCHEMA_VERSION,
    RunRecord,
    SessionRecorder,
    dataset_digest,
    environment_fingerprint,
    knobs_from_args,
    stream_dir,
)
from coda_tpu.telemetry.slo import SLObjective, SloSweeper, default_fleet_slos
from coda_tpu.telemetry.spans import SpanRecorder, annotation, stitch_traces
from coda_tpu.telemetry.trace import TRACE_HEADER, TraceContext
from coda_tpu.telemetry.trace import mint as mint_trace
from coda_tpu.telemetry.trace import parse as parse_trace

__all__ = [
    "COSTS",
    "CROSS_BACKEND_SCORE_TOL",
    "CalibrationMonitor",
    "CostBook",
    "CostTracked",
    "Counter",
    "CusumDetector",
    "DriftBank",
    "Gauge",
    "PageHinkley",
    "QualityPlane",
    "RECORD_SCHEMA_VERSION",
    "Registry",
    "RunRecord",
    "SLObjective",
    "SessionRecorder",
    "ShadowAuditor",
    "SloSweeper",
    "SpanRecorder",
    "TRACE_HEADER",
    "Telemetry",
    "TraceContext",
    "analyze_compiled",
    "annotation",
    "aot_call",
    "dataset_digest",
    "default_fleet_slos",
    "environment_fingerprint",
    "get_registry",
    "harvest_executable_cost",
    "install_jax_hooks",
    "jax_hooks_installed",
    "knobs_from_args",
    "lint_prometheus",
    "mint_trace",
    "parse_trace",
    "pbest_calibration",
    "quality_slos",
    "registry_hooked",
    "reliability_curve",
    "render_prometheus",
    "roofline",
    "sample_device_memory",
    "stitch_traces",
    "stream_dir",
]


class Telemetry:
    """Span recorder + registry + artifact writer, bundled for plumbing.

    ``out_dir=None`` keeps everything in memory (the serving layer serves
    ``/metrics`` from the registry without ever writing a file); with an
    ``out_dir``, :meth:`write` drops the run's artifacts there. The
    registry defaults to the process-wide one so recompile/HBM evidence
    aggregates across runners in one process.
    """

    def __init__(self, out_dir: Optional[str] = None,
                 registry: Optional[Registry] = None,
                 spans: Optional[SpanRecorder] = None,
                 install_hooks: bool = True):
        self.out_dir = out_dir
        self.registry = registry if registry is not None else get_registry()
        self.spans = spans if spans is not None else SpanRecorder()
        # hooks_live is per-REGISTRY truth: with install_hooks=False the
        # claim must not ride on some other registry's subscription
        self.hooks_live = install_jax_hooks(self.registry) \
            if install_hooks else registry_hooked(self.registry)
        # crash safety: a run that dies mid-flight (unhandled exception,
        # SIGTERM-turned-exit) must not lose its telemetry artifacts, so an
        # out_dir registers an atexit fallback that flushes IF nothing was
        # flushed explicitly. An orderly write()/__exit__ marks the flush
        # done and retires the fallback.
        self._flushed = False
        self._atexit_live = False
        if self.out_dir:
            atexit.register(self._atexit_flush)
            self._atexit_live = True

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # flush on BOTH clean and exceptional exits (the artifacts of a
        # failed run are the ones you want most); never swallow the error
        self.write()
        return False

    def _atexit_flush(self) -> None:
        if self._flushed or not self.out_dir:
            return
        try:
            self.write()
        except Exception:
            pass  # interpreter is going down; never mask the real exit

    def _retire_atexit(self) -> None:
        if self._atexit_live:
            try:
                atexit.unregister(self._atexit_flush)
            except Exception:
                pass
            self._atexit_live = False

    # -- recording passthroughs -------------------------------------------
    def span(self, name: str, lane: str = "host", annotate: bool = False,
             **attrs):
        return self.spans.span(name, lane=lane, annotate=annotate, **attrs)

    def counter(self, name: str, help: str = "") -> Counter:
        return self.registry.counter(name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self.registry.gauge(name, help)

    def sample_devices(self, devices=None) -> dict:
        return sample_device_memory(self.registry, devices)

    # -- reading / artifacts ----------------------------------------------
    def snapshot(self, extra: Optional[dict] = None) -> dict:
        """The ``telemetry.json`` payload: counters/gauges (recompiles, HBM
        watermarks), span summary, and the evidence source for each."""
        reg = self.registry.snapshot()

        def _values(name):
            return (reg.get(name) or {}).get("values", {})

        snap = {
            "metrics": reg,
            "jit": {
                "recompiles": _values("jit_compiles_total").get("", 0.0),
                "compile_seconds": _values(
                    "jit_compile_seconds_total").get("", 0.0),
                # with a persistent compilation cache live, the compile
                # event above also fires for deserializations — the
                # hit/miss split is the fresh-compile truth (the serve
                # warm-restart contract asserts on misses)
                "persistent_cache_hits": _values(
                    "persistent_cache_hits_total").get("", 0.0),
                "persistent_cache_misses": _values(
                    "persistent_cache_misses_total").get("", 0.0),
                "source": ("jax.monitoring" if self.hooks_live
                           else "cold-attribution-fallback"),
                "cold_dispatches": _values(
                    "suite_cold_dispatches_total").get("", 0.0),
            },
            "devices": {
                dev.split("=", 1)[1]: {"peak_bytes_in_use": v}
                for dev, v in _values("device_peak_bytes").items()
            },
            "spans": self.spans.summary(),
            # per-executable XLA cost attribution (telemetry/costs.py):
            # every compiled program harvested this process — FLOPs, bytes
            # accessed, peak working set, roofline class — keyed by site
            # (serve warm pool / suite / engine / bench)
            "costs": COSTS.snapshot(),
        }
        if extra:
            snap.update(extra)
        return snap

    def write(self, extra: Optional[dict] = None) -> dict:
        """Write ``trace.json`` / ``telemetry.json`` / ``metrics.prom``
        under ``out_dir``; returns {artifact: path} (empty without a dir)."""
        if not self.out_dir:
            return {}
        os.makedirs(self.out_dir, exist_ok=True)
        paths = {
            "trace": os.path.join(self.out_dir, "trace.json"),
            "telemetry": os.path.join(self.out_dir, "telemetry.json"),
            "prometheus": os.path.join(self.out_dir, "metrics.prom"),
        }
        self.spans.save(paths["trace"])
        with open(paths["telemetry"], "w") as f:
            json.dump(self.snapshot(extra), f, indent=2)
        with open(paths["prometheus"], "w") as f:
            f.write(render_prometheus(self.registry))
        self._flushed = True
        self._retire_atexit()
        return paths

    def flush_to_store(self, store, experiment: str = "telemetry",
                       run_name: Optional[str] = None,
                       params: Optional[dict] = None) -> str:
        """Flush the scalar registry into the MLflow-schema tracking store
        (same experiment -> run layout as benchmark metrics, so telemetry
        rows sit next to regret curves in one sqlite DB)."""
        name = run_name or f"{experiment}-telemetry"
        with store.run(experiment, name, params=params or {}) as run:
            for m in self.registry.collect():
                for labels, value in m.samples():
                    key = m.name
                    if labels:
                        key += "." + ".".join(
                            f"{k}_{v}" for k, v in sorted(labels.items()))
                    run.log_metric(key, float(value))
            spans = self.spans.summary()
            # total recorded, not ring-resident: long runs wrap the ring
            # and the DB row must not understate the span evidence
            run.log_metric("span_events", float(spans["recorded"]))
            run.log_metric("span_events_dropped", float(spans["dropped"]))
        return run.run_uuid
