"""Per-executable XLA cost attribution + roofline classification.

Every performance decision this repo makes — where an amortized Dirichlet
approximation would pay at C=1000, whether a serve tick is compute- or
HBM-bound, which suite dispatch deserves a bigger device — ultimately asks
the same question of a *compiled executable*: how many FLOPs does it do,
how many bytes does it move, and which side of the machine balance does
that put it on? Until now the answer lived in NOTES files, derived by
hand. This module makes it a harvested, machine-readable field:

  * :func:`analyze_compiled` reads XLA's own ``cost_analysis()`` /
    ``memory_analysis()`` off a ``jax.stages.Compiled`` — FLOPs, bytes
    accessed, argument/output/temp buffer sizes, and a peak-HBM estimate
    (arguments + outputs + temporaries, the executable's resident
    working set);
  * :func:`roofline` classifies the executable against a small
    per-device-kind peak table (the one table shared with ``bench.py``'s
    MFU/MBU math): arithmetic intensity below the machine balance means
    HBM-bound, above means compute-bound. Unknown device kinds (CPU
    containers) fall back to a documented generic host balance so the
    *classification* still exists — the peak fields stay honest (absent);
  * :class:`CostBook` is the process-wide ledger every harvest lands in,
    surfaced as the ``costs`` section of ``telemetry.json``, as per-bucket
    ``cost`` blocks on serve ``/stats``, and as ``executable_*`` gauge
    families on ``/metrics``.

Harvest sites (the three compile sites of the stack):

  * **serve warm pool** — ``Bucket.warm()`` already AOT-compiles every
    slab-step/init/pbest/write executable; harvesting there is free;
  * **suite / scheduler** — :class:`CostTracked` wraps the runner's jitted
    experiment programs: the first call per argument signature compiles
    ahead-of-time (``lower().compile()`` — the same compile the jit cache
    would have paid, through the same persistent compilation cache) and
    harvests the cost analysis; later calls reuse the compiled executable.
    Per-device scheduler placements key separate signatures, so each
    device's executable is attributed individually;
  * **engine entry** — :func:`aot_call` does the same for the one-shot
    ``run_seeds_compiled`` / ``run_seeds_recorded`` programs the CLI runs.

Caveat carried from ``bench.py``: XLA's FLOP counter counts ``lax.scan`` /
``lax.map`` bodies ONCE regardless of trip count, so a whole-experiment
executable's ``flops`` is not per-step work — it is the per-*invocation*
program profile, comparable across executables and rounds, which is what
regression gating and placement decisions need. Per-step rooflines stay
the analytic models' job (``bench.py``).

Every helper here is best-effort: a backend without cost analysis (or a
lowering that refuses AOT) degrades to the plain jit path and records
nothing — cost attribution must never be able to fail a run.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional

# -- the per-chip peak table (moved from bench.py; ONE definition) ----------

# published peak dense-matmul FLOP/s per chip (bf16); fp32 on the MXU runs
# at a fraction of this, so fp32 MFU vs the bf16 peak is a conservative
# lower bound on how well a kernel uses the hardware
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# published HBM bandwidth per chip (bytes/s) — the denominator of MBU and
# the other axis of the machine balance
PEAK_HBM_BPS = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}

# machine balance (FLOP/byte) fallback for device kinds not in the table
# (CPU containers, future chips before their entry lands): a generic
# server-CPU ballpark — tens of fp32 GFLOP/s against ~10 GB/s of per-core
# memory bandwidth. Coarse by design; entries in the tables above always
# win, and the ``peak_source`` field says which was used so a CPU-container
# roofline class is never mistaken for silicon evidence.
DEFAULT_MACHINE_BALANCE = 8.0


def peaks_for(device_kind: Optional[str]) -> dict:
    """Peak FLOP/s + HBM B/s for a device kind (None values if unknown)."""
    pf = PEAK_FLOPS.get(device_kind) if device_kind else None
    pb = PEAK_HBM_BPS.get(device_kind) if device_kind else None
    return {"peak_flops_per_sec": pf, "peak_hbm_bytes_per_sec": pb,
            "peak_source": "table" if (pf and pb) else "default_balance"}


def roofline(flops: float, bytes_accessed: float,
             device_kind: Optional[str] = None) -> dict:
    """Arithmetic intensity vs machine balance -> bound classification.

    ``class`` is ``compute-bound`` when the executable's FLOP/byte ratio
    clears the device's machine balance, ``memory-bound`` below it, and
    ``unknown`` when XLA reported no byte traffic to divide by. With an
    unknown device kind the balance falls back to
    :data:`DEFAULT_MACHINE_BALANCE` (``peak_source: default_balance``).
    """
    peaks = peaks_for(device_kind)
    pf, pb = peaks["peak_flops_per_sec"], peaks["peak_hbm_bytes_per_sec"]
    balance = (pf / pb) if (pf and pb) else DEFAULT_MACHINE_BALANCE
    flops = max(0.0, float(flops or 0.0))
    bytes_accessed = max(0.0, float(bytes_accessed or 0.0))
    if bytes_accessed <= 0.0:
        cls, ai = "unknown", 0.0
    else:
        ai = flops / bytes_accessed
        cls = "compute-bound" if ai >= balance else "memory-bound"
    return {
        "arithmetic_intensity": ai,
        "machine_balance": balance,
        "roofline_class": cls,
        **peaks,
    }


def analyze_compiled(compiled) -> Optional[dict]:
    """XLA cost + memory analysis of one compiled executable, or None.

    ``flops`` / ``bytes accessed`` come from ``cost_analysis()`` (list-of-
    dicts on older APIs), buffer sizes from ``memory_analysis()``;
    ``peak_hbm_bytes`` is arguments + outputs + temporaries + aliases —
    the executable's device-resident working set, the number the HBM
    budgeting (scheduler ``max_inflight``, serve capacity) reasons about.
    """
    out: dict = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        out["flops"] = max(0.0, float(cost.get("flops", 0.0)))
        out["bytes_accessed"] = max(
            0.0, float(cost.get("bytes accessed", 0.0)))
    except Exception:
        return None
    try:
        ma = compiled.memory_analysis()
        arg = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
        res = float(getattr(ma, "output_size_in_bytes", 0) or 0)
        tmp = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
        ali = float(getattr(ma, "alias_size_in_bytes", 0) or 0)
        out.update(argument_bytes=arg, output_bytes=res, temp_bytes=tmp,
                   generated_code_bytes=float(
                       getattr(ma, "generated_code_size_in_bytes", 0) or 0),
                   peak_hbm_bytes=arg + res + tmp + ali)
    except Exception:
        # cost without memory is still worth recording (older runtimes)
        out.update(argument_bytes=None, output_bytes=None, temp_bytes=None,
                   generated_code_bytes=None, peak_hbm_bytes=None)
    return out


def _default_device_kind() -> Optional[str]:
    try:
        import jax

        devs = jax.devices()
        return devs[0].device_kind if devs else None
    except Exception:
        return None


# -- the process-wide cost ledger -------------------------------------------

class CostBook:
    """Thread-safe ledger of harvested executables: name -> cost entry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}

    def record(self, name: str, entry: dict) -> None:
        with self._lock:
            self._entries[name] = dict(entry)

    def get(self, name: str) -> Optional[dict]:
        with self._lock:
            e = self._entries.get(name)
            return dict(e) if e is not None else None

    def snapshot(self, site: Optional[str] = None) -> dict:
        """JSON-able {name: entry}, optionally filtered to one harvest
        site (``serve`` | ``suite`` | ``engine`` | ``bench``)."""
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._entries.items())
                    if site is None or v.get("site") == site}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


COSTS = CostBook()

_ENABLED = True


def set_enabled(flag: bool) -> None:
    """Process-wide kill switch (``--no-cost-capture``): harvesting AND
    the AOT-compile-and-reuse wrappers degrade to the plain jit path."""
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def _feed_gauges(name: str, entry: dict, registry=None) -> None:
    from coda_tpu.telemetry.registry import get_registry

    reg = registry if registry is not None else get_registry()
    labels = {"site": entry.get("site", ""), "name": name}
    reg.gauge("executable_flops",
              "XLA cost-model FLOPs of a compiled executable (scan/map "
              "bodies counted once)").set(entry["flops"], **labels)
    reg.gauge("executable_bytes_accessed",
              "XLA cost-model bytes accessed by a compiled "
              "executable").set(entry["bytes_accessed"], **labels)
    if entry.get("peak_hbm_bytes") is not None:
        reg.gauge("executable_peak_hbm_bytes",
                  "Device-resident working set of a compiled executable "
                  "(arguments + outputs + temporaries)").set(
                      entry["peak_hbm_bytes"], **labels)
    reg.gauge("executable_arithmetic_intensity",
              "FLOPs per byte accessed of a compiled executable").set(
                  entry["arithmetic_intensity"], **labels)
    reg.gauge("executable_roofline",
              "Roofline classification marker (value is always 1; the "
              "class label carries the verdict)").set(
                  1.0, **labels, **{"class": entry["roofline_class"]})


def harvest(compiled, name: str, site: str = "engine",
            device_kind: Optional[str] = None, registry=None,
            extra: Optional[dict] = None) -> Optional[dict]:
    """Analyze + classify + ledger one compiled executable. Never raises;
    returns the recorded entry (or None when analysis is unavailable)."""
    if not _ENABLED:
        return None
    try:
        xla = analyze_compiled(compiled)
        if xla is None:
            return None
        if device_kind is None:
            device_kind = _default_device_kind()
        entry = {"site": site, "device_kind": device_kind, **xla,
                 **roofline(xla["flops"], xla["bytes_accessed"],
                            device_kind)}
        if extra:
            entry.update(extra)
        COSTS.record(name, entry)
        _feed_gauges(name, entry, registry)
        return entry
    except Exception:
        return None


# -- harvest-at-compile wrappers --------------------------------------------

def _leaf_sig(x) -> tuple:
    shape = tuple(getattr(x, "shape", ()) or ())
    dtype = str(getattr(x, "dtype", type(x).__name__))
    try:
        devs = tuple(sorted(str(d) for d in x.devices()))
    except Exception:
        devs = ()
    return (shape, dtype, devs)


def _signature(args: tuple) -> tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (str(treedef),) + tuple(_leaf_sig(x) for x in leaves)


def _sig_tag(sig: tuple) -> str:
    return hashlib.sha256(repr(sig).encode()).hexdigest()[:8]


class CostTracked:
    """Wrap a jitted function so every distinct argument signature is
    compiled ahead-of-time ONCE and cost-harvested.

    Call-compatible with the jit function it wraps (the suite runner's
    ``_jitted`` cache stores these). Compilation cost is identical to the
    jit path it replaces — one XLA compile per signature, served by the
    same persistent compilation cache — and the compiled program is the
    same HLO, so results are bitwise those of the lazy-jit path (the same
    contract the serve warm pool is pinned on). Any AOT failure (an
    argument XLA refuses to lower ahead-of-time, an aval mismatch at call
    time) falls back to the plain jit call for that signature, recorded as
    ``aot: false`` so coverage gaps are visible, never silent.
    """

    def __init__(self, jit_fn, name: str, site: str = "suite",
                 registry=None, extra: Optional[dict] = None):
        self._jit = jit_fn
        self._name = name
        self._site = site
        self._registry = registry
        self._extra = extra
        self._lock = threading.Lock()
        self._compiled: dict = {}   # signature -> Compiled | None(fallback)

    def __call__(self, *args):
        if not _ENABLED:
            return self._jit(*args)
        try:
            sig = _signature(args)
        except Exception:
            return self._jit(*args)
        with self._lock:
            known = sig in self._compiled
            compiled = self._compiled.get(sig)
        if not known:
            compiled = self._compile(sig, args)
        if compiled is None:
            return self._jit(*args)
        try:
            return compiled(*args)
        except Exception:
            # aval/sharding mismatch the signature didn't key: degrade this
            # signature to the jit path permanently — and overwrite the
            # harvested entry so the book never implies an AOT-attributed
            # program that actually runs lazy (the never-silent contract)
            with self._lock:
                self._compiled[sig] = None
            COSTS.record(f"{self._name}@{_sig_tag(sig)}",
                         {"site": self._site, "aot": False,
                          "degraded": "call"})
            return self._jit(*args)

    def _compile(self, sig: tuple, args: tuple):
        try:
            compiled = self._jit.lower(*args).compile()
        except Exception:
            compiled = None
        with self._lock:
            self._compiled[sig] = compiled
        name = f"{self._name}@{_sig_tag(sig)}"
        if compiled is not None:
            extra = dict(self._extra or {})
            extra["signature"] = [list(map(str, s)) for s in sig[1:]]
            harvest(compiled, name, site=self._site,
                    registry=self._registry, extra=extra)
        else:
            COSTS.record(name, {"site": self._site, "aot": False})
        return compiled


# package-level alias: `from coda_tpu.telemetry import
# harvest_executable_cost` reads better than a bare `harvest`
harvest_executable_cost = harvest


def aot_call(jit_fn, args: tuple, name: str, site: str = "engine",
             registry=None, extra: Optional[dict] = None):
    """One-shot AOT-compile + harvest + execute (the engine entry's
    ``jax.jit(fn)(*args)`` with cost attribution). The jit path is the
    fallback for anything AOT refuses."""
    if not _ENABLED:
        return jit_fn(*args)
    try:
        compiled = jit_fn.lower(*args).compile()
    except Exception:
        return jit_fn(*args)
    harvest(compiled, name, site=site, registry=registry, extra=extra)
    try:
        return compiled(*args)
    except Exception:
        return jit_fn(*args)
