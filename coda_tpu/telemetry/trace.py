"""Trace context: mint/parse/propagate one causal id per label decision.

A trace context is an immutable ``(trace_id, span_id, parent)`` triple,
W3C-traceparent-shaped but deliberately smaller: the serve stack only ever
crosses one trust boundary (client -> router -> replica), so the flags and
version fields buy nothing. The wire form is one HTTP header::

    coda-trace: <trace_id>-<span_id>

where ``trace_id`` is 16 bytes hex (the whole causal chain) and ``span_id``
is 8 bytes hex (the caller's span — the receiver records it as ``parent``
and mints a fresh ``span_id`` for its own work). ``InprocReplica`` passes
the parsed tuple as a keyword argument instead of serializing; both roads
meet in the replica verb, preserving the transport parity contract.

Design rule (the non-perturbation contract, pinned by
``tests/test_observability.py``): a trace context may touch *tickets, spans,
metrics and recorder rows* — never session state, PRNG keys, or posterior
math. With tracing off every code path sees ``None`` and takes the exact
branch it took before this module existed.
"""

from __future__ import annotations

import os
import re
from typing import NamedTuple, Optional

# HTTP header carrying the context (lower-case: our parser lower-cases all
# header names, and urllib title-cases on send — match case-insensitively)
TRACE_HEADER = "coda-trace"

_HEX = re.compile(r"^[0-9a-f]+$")


class TraceContext(NamedTuple):
    """One hop of a causal chain. ``parent`` is the caller's span_id
    (empty string at the front door)."""
    trace_id: str
    span_id: str
    parent: str = ""

    def header(self) -> str:
        """Wire form for the ``coda-trace`` header (parent is implicit:
        the receiver treats our ``span_id`` as its parent)."""
        return f"{self.trace_id}-{self.span_id}"

    def child(self) -> "TraceContext":
        """Fresh span under the same trace, parented to this span."""
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def attrs(self) -> dict:
        """Span-recorder attrs for this context (the keys the per-trace
        retention index and the stitcher key off)."""
        d = {"trace": self.trace_id, "span": self.span_id}
        if self.parent:
            d["parent"] = self.parent
        return d


def _new_span_id() -> str:
    return os.urandom(8).hex()


def mint() -> TraceContext:
    """Front-door mint: fresh trace, fresh root span, no parent."""
    return TraceContext(os.urandom(16).hex(), _new_span_id(), "")


def parse(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``coda-trace`` header value; ``None`` on anything malformed
    (a bad header must degrade to untraced, never to a 500)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 2:
        return None
    tid, sid = parts
    if len(tid) != 32 or len(sid) != 16:
        return None
    if not (_HEX.match(tid) and _HEX.match(sid)):
        return None
    return TraceContext(tid, sid, "")


def continue_from(ctx: Optional["TraceContext"]) -> Optional["TraceContext"]:
    """Receiver-side continuation: mint a child span under the caller's
    context, or ``None`` when the caller sent none (stay untraced)."""
    return ctx.child() if ctx is not None else None
