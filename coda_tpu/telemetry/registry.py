"""Process-wide counter/gauge registry with jit-recompile and HBM evidence.

Scheduler and kernel claims need utilization attribution that survives
skepticism on real chips: how many times did XLA actually (re)compile, and
how high did each device's HBM watermark go? Both are observable:

  * **Recompiles** — ``jax.monitoring`` emits a duration event per backend
    compile (``/jax/core/compile/backend_compile_duration``); subscribing
    once per process gives an exact compile count + summed compile seconds.
    Where the hook is unavailable (older jax, stripped builds) the suite's
    timing-based cold/warm attribution still feeds
    ``suite_cold_dispatches_total``, so cold evidence never goes dark.
  * **HBM watermarks** — ``device.memory_stats()`` after each dispatch
    (``bytes_in_use`` / ``peak_bytes_in_use``); gracefully absent on
    backends that return ``None`` (CPU), so CPU runs simply report no
    device gauges instead of failing.

Metrics live in one process-wide :data:`REGISTRY` (like jax's own compile
cache, telemetry is per-process), rendered by
:mod:`coda_tpu.telemetry.prometheus` and dumped into ``telemetry.json`` by
the :class:`~coda_tpu.telemetry.Telemetry` facade.
"""

from __future__ import annotations

import threading
import weakref
from typing import Iterable, Optional


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """One named metric family: a value per label-set, under one lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> list[tuple[dict, float]]:
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def set_max(self, value: float, **labels) -> None:
        """Watermark semantics: keep the max ever observed."""
        k = _label_key(labels)
        with self._lock:
            self._values[k] = max(self._values.get(k, float("-inf")),
                                  float(value))


class Registry:
    """Create-or-get metric families by name (process-wide by default)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def collect(self) -> Iterable[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """JSON-able dump: {name: {kind, help, values: {labelrepr: v}}}."""
        out = {}
        for m in self.collect():
            values = {}
            for labels, v in m.samples():
                key = ",".join(f"{k}={val}" for k, val in
                               sorted(labels.items())) or ""
                values[key] = v
            out[m.name] = {"kind": m.kind, "help": m.help, "values": values}
        return out


REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY


# -- jit compile hooks -------------------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# persistent-compilation-cache evidence: in jax's event stream the
# backend_compile duration above fires for BOTH fresh compiles and
# cache-deserialized executables (it wraps compile_or_get_cached), so the
# hit/miss events are the only way to count FRESH compiles when a
# --compilation-cache-dir is live — the serve warm-pool restart contract
# ("second start performs 0 fresh backend compiles") asserts on the miss
# counter, not on jit_compiles_total
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_hooks_lock = threading.Lock()
_listener_registered = False
# every registry that asked for compile evidence; jax.monitoring has no
# per-listener unregister, so ONE listener fans out to however many live
# registries are hooked (weak: a dropped test registry must not leak)
_hooked_registries: "weakref.WeakSet[Registry]" = weakref.WeakSet()


def jax_hooks_installed() -> bool:
    return _listener_registered


def registry_hooked(registry: Optional[Registry] = None) -> bool:
    """Whether THIS registry receives compile events."""
    return (registry or REGISTRY) in _hooked_registries


def _on_compile_duration(event: str, duration: float, **kw) -> None:
    if event != _COMPILE_EVENT:
        return
    # snapshot under the lock: a concurrent install_jax_hooks add would
    # otherwise race the WeakSet iteration (RuntimeError mid-listener)
    with _hooks_lock:
        regs = list(_hooked_registries)
    for reg in regs:
        reg.counter(
            "jit_compiles_total",
            "XLA backend compiles observed via jax.monitoring "
            "(includes persistent-cache deserializations)").inc()
        reg.counter(
            "jit_compile_seconds_total",
            "Seconds spent in XLA backend compiles").inc(
                max(0.0, float(duration)))


def _on_event(event: str, **kw) -> None:
    if event == _CACHE_HIT_EVENT:
        name, help = ("persistent_cache_hits_total",
                      "Executables deserialized from the persistent "
                      "compilation cache instead of freshly compiled")
    elif event == _CACHE_MISS_EVENT:
        name, help = ("persistent_cache_misses_total",
                      "Fresh XLA compiles performed with a persistent "
                      "compilation cache live (cache misses)")
    else:
        return
    with _hooks_lock:
        regs = list(_hooked_registries)
    for reg in regs:
        reg.counter(name, help).inc()


def install_jax_hooks(registry: Optional[Registry] = None) -> bool:
    """Subscribe ``registry``'s recompile counters to ``jax.monitoring``.

    Idempotent per registry; returns whether THIS registry now receives
    compile events (False -> callers fall back to the suite's
    cold-attribution counters alone)."""
    global _listener_registered
    reg = registry or REGISTRY
    with _hooks_lock:
        if reg in _hooked_registries:
            return True
        if not _listener_registered:
            try:
                from jax import monitoring

                monitoring.register_event_duration_secs_listener(
                    _on_compile_duration)
            except Exception:
                return False
            try:
                # plain (non-duration) events: persistent-cache hits/misses.
                # Best-effort — a jax without them still counts compiles.
                monitoring.register_event_listener(_on_event)
            except Exception:
                pass
            _listener_registered = True
        _hooked_registries.add(reg)
        return True


# -- device memory sampling --------------------------------------------------

def _read_rss_bytes() -> Optional[int]:
    """Current process resident-set size, or None where unreadable."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        pass
    try:
        import resource
        import sys

        # ru_maxrss is the PEAK, not current — still honest memory
        # evidence on hosts without /proc. Units differ by platform:
        # bytes on macOS, KiB on Linux/BSD.
        scale = 1 if sys.platform == "darwin" else 1024
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
    except Exception:
        return None


def sample_process_rss(registry: Optional[Registry] = None) -> Optional[int]:
    """Record the process RSS gauge + watermark (``source="rss"``).

    The CPU-container fallback for memory evidence: ``memory_stats()`` is
    None there, so captures carried NO memory numbers at all. Host RSS is
    not device HBM — the ``source`` label keeps the two families distinct
    (``device_*`` gauges stay strictly ``memory_stats()``-backed) — but it
    bounds the working set the same artifacts need to reason about."""
    reg = registry or REGISTRY
    rss = _read_rss_bytes()
    if rss is None:
        return None
    reg.gauge("process_rss_bytes",
              "Resident-set size of this process (host memory; the "
              "CPU-container fallback for device memory evidence)").set(
                  float(rss), source="rss")
    reg.gauge("process_peak_rss_bytes",
              "High-water process RSS across samples").set_max(
                  float(rss), source="rss")
    return int(rss)


def sample_device_memory(registry: Optional[Registry] = None,
                         devices=None) -> dict:
    """Record per-device HBM gauges + watermarks; returns what was sampled.

    ``{device_id: {bytes_in_use, peak_bytes_in_use}}`` — empty on backends
    whose ``memory_stats()`` is ``None`` (CPU) or missing. Called after each
    dispatch by the suite/scheduler harvest; O(devices) dict reads, no
    device sync."""
    reg = registry or REGISTRY
    if devices is None:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return {}
    in_use = reg.gauge("device_bytes_in_use",
                       "Device memory currently allocated (memory_stats)")
    peak = reg.gauge("device_peak_bytes",
                     "High-water device memory mark across samples")
    out: dict = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        used = stats.get("bytes_in_use")
        if used is None:
            continue
        dev = str(getattr(d, "id", d))
        in_use.set(float(used), device=dev)
        # prefer the allocator's own peak when exposed; our max-of-samples
        # watermark is the fallback evidence on backends without it
        pk = stats.get("peak_bytes_in_use", used)
        peak.set_max(float(pk), device=dev)
        out[dev] = {"bytes_in_use": int(used),
                    "peak_bytes_in_use": int(pk)}
    if not out:
        # no device reported memory_stats (CPU backend): fall back to
        # process RSS so the capture still carries memory evidence. The
        # returned dict stays device-only — RSS is a registry gauge, not a
        # device sample.
        sample_process_rss(reg)
    return out
