"""Structured span recorder: one timeline for host phases and device lanes.

The stack has three independent hot loops — the compiled experiment scan
(`engine/loop.py`), the multi-device suite scheduler (`engine/scheduler.py`)
and the serving batcher tick (`serve/batcher.py`) — and before this module
each reported time its own way (``StepTimer`` totals, ``last_stats`` dicts,
latency rings). A :class:`SpanRecorder` gives them ONE vocabulary: named
begin/end events on named *lanes* (one lane per device, plus host lanes),
recorded O(1) into a fixed-capacity ring like ``ServeMetrics``' latency
rings — no allocation growth, no reduction in the record path — and exported
as Chrome ``trace_event`` JSON, loadable in Perfetto / ``chrome://tracing``.

Host spans and ``--profile-dir`` device traces line up because hot regions
also enter :func:`annotation` (``jax.profiler.TraceAnnotation``), which
stamps the same names into the profiler's host rows; ``jax.named_scope``
inside traced code does the counterpart for device-side HLO metadata.

All timestamps come from ``time.perf_counter()`` (monotonic) relative to the
recorder's creation — never wall clock (``scripts/check_clocks.py`` enforces
this repo-wide).
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from typing import Optional

# events kept per recorder: enough for a full 26-task suite sweep
# (~hundreds of dispatch spans) plus long serve sessions' tick spans,
# small enough that a trace.json export stays a few MB
_CAPACITY = 65536

# per-trace retention ring: distinct traces kept (FIFO eviction) and spans
# kept per trace. The front door mints a context for EVERY session verb,
# so a loadgen capture run generates thousands of traces — the cap must
# outlast a full capture pass or sampled traces are evicted before the
# stitcher fetches them. Both caps bound memory independently of the main
# ring (4096 traces x 256 spans x ~100 B is a few-MB worst case).
_TRACE_CAPACITY = 4096
_TRACE_SPAN_CAPACITY = 256


@contextlib.contextmanager
def annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when jax is importable, else no-op.

    Used around HOST-side hot regions (scheduler dispatch, batcher tick) so
    a concurrently-running ``--profile-dir`` capture shows the same span
    names as our ``trace.json`` — the correlation hook between the two.
    """
    try:
        from jax.profiler import TraceAnnotation
    except Exception:  # jax absent or too old: spans still record
        yield
        return
    with TraceAnnotation(name):
        yield


class SpanRecorder:
    """Thread-safe structured span recorder with Chrome-trace export.

    Lanes are created on first use and map to Chrome ``tid``s in first-seen
    order; use ``device:<id>`` for device lanes and ``host:<role>`` for host
    threads. Events are ``(name, lane, t_start, t_end, attrs)`` tuples in a
    bounded ring — recording is O(1) and never blocks on a reduction.
    """

    def __init__(self, capacity: int = _CAPACITY,
                 trace_capacity: int = _TRACE_CAPACITY,
                 trace_span_capacity: int = _TRACE_SPAN_CAPACITY):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lanes: dict[str, int] = {}
        self._t0 = time.perf_counter()
        # wall-clock: one-shot anchor pairing _t0 with an epoch instant so a
        # router can line up spans from recorders in different processes;
        # never used for durations (those stay perf_counter-relative)
        self._t0_unix = time.time()  # wall-clock: cross-process anchor
        self.capacity = capacity
        self.recorded = 0  # total ever recorded (ring evicts past capacity)
        # trace_id -> deque of event tuples; FIFO eviction past capacity
        self._traces: "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        self._trace_capacity = trace_capacity
        self._trace_span_capacity = trace_span_capacity

    # -- recording (hot path: O(1)) ----------------------------------------
    def record(self, name: str, lane: str = "host", t_start: float = 0.0,
               t_end: float = 0.0, attrs: Optional[dict] = None) -> None:
        """Record one completed span (perf_counter begin/end seconds).

        ``attrs["trace"]`` indexes the span under that trace for
        :meth:`trace_events`; ``attrs["links"]`` (a list of trace_ids)
        additionally files it under every linked trace — the OTel span-link
        fan-in a coalesced batcher tick uses, so a tick serving 32 requests
        appears in all 32 traces while being recorded exactly once.
        """
        with self._lock:
            if lane not in self._lanes:
                self._lanes[lane] = len(self._lanes)
            ev = (name, lane, t_start, t_end, attrs)
            self._events.append(ev)
            self.recorded += 1
            if attrs:
                tid = attrs.get("trace")
                if tid is not None:
                    self._index_trace(tid, ev)
                for linked in attrs.get("links") or ():
                    if linked != tid:
                        self._index_trace(linked, ev)

    def _index_trace(self, trace_id: str, ev: tuple) -> None:
        """File one event under a trace id (caller holds the lock)."""
        ring = self._traces.get(trace_id)
        if ring is None:
            while len(self._traces) >= self._trace_capacity:
                self._traces.popitem(last=False)
            ring = collections.deque(maxlen=self._trace_span_capacity)
            self._traces[trace_id] = ring
        ring.append(ev)

    def instant(self, name: str, lane: str = "host",
                attrs: Optional[dict] = None) -> None:
        """Record a zero-duration marker event."""
        t = time.perf_counter()
        self.record(name, lane, t, t, attrs)

    @contextlib.contextmanager
    def span(self, name: str, lane: str = "host", annotate: bool = False,
             **attrs):
        """Time the enclosed block as one span on ``lane``.

        ``annotate=True`` additionally enters :func:`annotation` so the
        region shows up (same name) in a live ``jax.profiler`` capture.
        """
        cm = annotation(name) if annotate else contextlib.nullcontext()
        t0 = time.perf_counter()
        try:
            with cm:
                yield
        finally:
            self.record(name, lane, t0, time.perf_counter(), attrs or None)

    # -- reading -----------------------------------------------------------
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def lanes(self) -> list[str]:
        """Lane names in tid order."""
        with self._lock:
            return sorted(self._lanes, key=self._lanes.get)

    def summary(self) -> dict:
        with self._lock:
            return {
                "events": len(self._events),
                "recorded": self.recorded,
                "dropped": max(0, self.recorded - len(self._events)),
                "capacity": self.capacity,
                "lanes": sorted(self._lanes, key=self._lanes.get),
            }

    def trace_ids(self) -> list[str]:
        """Retained trace ids, oldest first."""
        with self._lock:
            return list(self._traces)

    def trace_events(self, trace_id: str) -> list:
        """Retained event tuples for one trace (empty if unknown/evicted)."""
        with self._lock:
            ring = self._traces.get(trace_id)
            return list(ring) if ring is not None else []

    def trace_payload(self, trace_id: str, process: str = "") -> dict:
        """Wire payload for ``GET /trace/id/{trace_id}``: this recorder's
        retained spans for one trace, timestamps rebased to seconds since
        recorder creation plus a wall-clock anchor (``t0_unix``) so a
        stitcher can line up recorders from different processes."""
        events = [
            {"name": name, "lane": lane,
             "t0": t0 - self._t0, "t1": t1 - self._t0,
             **({"attrs": attrs} if attrs else {})}
            for name, lane, t0, t1, attrs in self.trace_events(trace_id)
        ]
        return {"trace_id": trace_id, "process": process,
                "t0_unix": self._t0_unix, "events": events}

    def lane_busy_s(self, lane: str) -> float:
        """Union-of-intervals busy seconds of one lane (overlapping spans
        counted once — the same folding the scheduler's occupancy uses)."""
        ivals = sorted((t0, t1) for name, ln, t0, t1, _ in self.events()
                       if ln == lane)
        busy, last = 0.0, None
        for s, e in ivals:
            if last is None or s > last:
                busy += e - s
                last = e
            elif e > last:
                busy += e - last
                last = e
        return busy

    # -- export ------------------------------------------------------------
    def to_chrome(self) -> dict:
        """Chrome ``trace_event`` JSON object (Perfetto-loadable).

        Spans become ``"X"`` (complete) events with microsecond timestamps
        relative to recorder creation; each lane is a named thread of one
        process, ordered by first use. Nested spans on a lane nest visually
        because their intervals nest.
        """
        with self._lock:
            events = list(self._events)
            lanes = dict(self._lanes)
        out = []
        for lane, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
            out.append({"name": "thread_name", "ph": "M", "pid": 0,
                        "tid": tid, "args": {"name": lane}})
            out.append({"name": "thread_sort_index", "ph": "M", "pid": 0,
                        "tid": tid, "args": {"sort_index": tid}})
        for name, lane, t0, t1, attrs in events:
            ev = {
                "name": name, "ph": "X", "pid": 0, "tid": lanes[lane],
                "ts": round((t0 - self._t0) * 1e6, 3),
                "dur": round(max(0.0, t1 - t0) * 1e6, 3),
            }
            if attrs:
                ev["args"] = attrs
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f)
        return path


def stitch_traces(payloads: list[dict]) -> dict:
    """Stitch per-process :meth:`SpanRecorder.trace_payload` dicts into one
    Chrome ``trace_event`` file with one *process lane* per payload.

    Each payload becomes a Chrome ``pid`` named after its ``process``
    (router, replica id, ...); lanes within a payload keep their tids.
    Timestamps are aligned across processes via each payload's wall-clock
    anchor, rebased so the earliest span in the stitched trace is t=0 —
    Perfetto then shows the router verb, both replicas' serve spans, and
    the linked tick/step spans on one shared timeline.
    """
    payloads = [p for p in payloads if p and p.get("events")]
    if not payloads:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # absolute (epoch) start of the earliest span across all processes
    base = min(p["t0_unix"] + e["t0"] for p in payloads for e in p["events"])
    out = []
    for pid, p in enumerate(payloads):
        name = p.get("process") or f"process-{pid}"
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": name}})
        out.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"sort_index": pid}})
        lanes: dict[str, int] = {}
        for e in p["events"]:
            lane = e.get("lane", "host")
            if lane not in lanes:
                lanes[lane] = len(lanes)
                out.append({"name": "thread_name", "ph": "M", "pid": pid,
                            "tid": lanes[lane], "args": {"name": lane}})
            off = p["t0_unix"] - base
            ev = {
                "name": e["name"], "ph": "X", "pid": pid,
                "tid": lanes[lane],
                "ts": round((e["t0"] + off) * 1e6, 3),
                "dur": round(max(0.0, e["t1"] - e["t0"]) * 1e6, 3),
            }
            if e.get("attrs"):
                ev["args"] = e["attrs"]
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}
