"""Prometheus text-exposition rendering of the telemetry registry.

One function, :func:`render`, turns the process-wide counter/gauge registry
(plus an optional :class:`~coda_tpu.serve.metrics.ServeMetrics`) into the
Prometheus text exposition format (version 0.0.4) — the payload the serving
layer's ``GET /metrics`` answers and batch runs can dump next to
``telemetry.json``. No client library: the format is lines of
``name{labels} value`` under ``# HELP`` / ``# TYPE`` headers, and writing it
directly keeps TPU images dependency-free (the same stance as the stdlib
HTTP server and the MLflow-schema sqlite store).
"""

from __future__ import annotations

import re
from typing import Optional

from coda_tpu.telemetry.registry import Registry, get_registry

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _name(prefix: str, name: str) -> str:
    n = f"{prefix}_{name}" if prefix else name
    n = _NAME_OK.sub("_", n)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    v = float(value)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _line(name: str, labels: dict, value: float) -> str:
    if labels:
        lab = ",".join(f'{_NAME_OK.sub("_", str(k))}="{_escape(v)}"'
                       for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def _family(out: list, name: str, kind: str, help: str,
            samples: list) -> None:
    if help:
        out.append(f"# HELP {name} {_escape(help)}")
    out.append(f"# TYPE {name} {kind}")
    for labels, value in samples:
        out.append(_line(name, labels, value))


def render(registry: Optional[Registry] = None, serve_metrics=None,
           prefix: str = "coda") -> str:
    """The registry (+ optional ServeMetrics snapshot) as exposition text."""
    out: list[str] = []
    reg = registry if registry is not None else get_registry()
    for m in reg.collect():
        _family(out, _name(prefix, m.name), m.kind, m.help, m.samples())
    if serve_metrics is not None:
        _render_serve(out, serve_metrics.snapshot(), prefix)
    return "\n".join(out) + "\n"


# (snapshot key, metric suffix, kind, help) — counters keep their
# monotonic-total names, distribution means/maxes surface as gauges
_SERVE_SCALARS = [
    ("uptime_s", "serve_uptime_seconds", "gauge",
     "Seconds since the serve metrics baseline (monotonic clock)"),
    ("dispatches", "serve_dispatches_total", "counter",
     "Compiled slab-step dispatches"),
    ("requests", "serve_requests_total", "counter",
     "Requests served across all dispatches"),
    ("sessions_opened", "serve_sessions_opened_total", "counter",
     "Sessions admitted"),
    ("sessions_closed", "serve_sessions_closed_total", "counter",
     "Sessions closed"),
    ("sessions_rejected", "serve_sessions_rejected_total", "counter",
     "Sessions refused by admission control (slab full / draining)"),
    ("requests_rejected", "serve_requests_rejected_total", "counter",
     "Requests refused (draining / unknown session / stale item)"),
    ("max_occupancy", "serve_max_occupancy", "gauge",
     "Most requests ever served by one dispatch"),
    ("mean_occupancy", "serve_mean_occupancy", "gauge",
     "Mean requests per dispatch over the recent ring"),
    ("mean_queue_depth", "serve_mean_queue_depth", "gauge",
     "Mean queue depth at tick start over the recent ring"),
    ("ring_capacity", "serve_ring_capacity", "gauge",
     "Capacity of each metrics ring (fill == capacity means wrapped)"),
]

_SERVE_SUMMARIES = [
    ("dispatch_latency", "serve_dispatch_latency_seconds", "dispatches",
     "Slab-step dispatch seconds over the recent ring"),
    ("request_latency", "serve_request_latency_seconds", "requests",
     "Submit-to-result request seconds over the recent ring"),
    ("queue_wait", "serve_queue_wait_seconds", "requests",
     "Submit-to-tick-start queue wait seconds over the recent ring"),
    ("step_latency", "serve_step_latency_seconds", "dispatches",
     "Compiled slab-step execution seconds over the recent ring"),
]

# warm-pool evidence: (warm_pool snapshot key, metric suffix, kind, help)
_SERVE_WARM = [
    ("size", "serve_warm_pool_size", "gauge",
     "AOT-precompiled executables in the warm pool"),
    ("warm_s", "serve_warm_pool_seconds", "gauge",
     "Wall seconds the warm-up pass took"),
    ("hits", "serve_warm_pool_hits_total", "counter",
     "Dispatches served by an AOT-precompiled executable"),
    ("misses", "serve_warm_pool_misses_total", "counter",
     "Dispatches that fell back to lazy jit compilation"),
]


def _render_serve(out: list, snap: dict, prefix: str) -> None:
    for key, suffix, kind, help in _SERVE_SCALARS:
        v = snap.get(key)
        if v is not None:
            _family(out, _name(prefix, suffix), kind, help, [({}, v)])
    warm = snap.get("warm_pool") or {}
    for key, suffix, kind, help in _SERVE_WARM:
        v = warm.get(key)
        if v is not None:
            _family(out, _name(prefix, suffix), kind, help, [({}, v)])
    fills = snap.get("ring_fill") or {}
    if fills:
        _family(out, _name(prefix, "serve_ring_fill"), "gauge",
                "Events currently held in a metrics ring",
                [({"ring": k}, n) for k, n in sorted(fills.items())])
    for key, suffix, count_key, help in _SERVE_SUMMARIES:
        q = snap.get(key) or {}
        name = _name(prefix, suffix)
        samples = []
        for qk, quantile in (("p50_ms", "0.5"), ("p99_ms", "0.99")):
            if q.get(qk) is not None:
                samples.append(({"quantile": quantile}, q[qk] / 1e3))
        if not samples:
            continue
        _family(out, name, "summary", help, samples)
        out.append(_line(name + "_count", {}, snap.get(count_key, 0)))
