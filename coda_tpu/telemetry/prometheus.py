"""Prometheus text-exposition rendering of the telemetry registry.

One function, :func:`render`, turns the process-wide counter/gauge registry
(plus an optional :class:`~coda_tpu.serve.metrics.ServeMetrics`) into the
Prometheus text exposition format (version 0.0.4) — the payload the serving
layer's ``GET /metrics`` answers and batch runs can dump next to
``telemetry.json``. No client library: the format is lines of
``name{labels} value`` under ``# HELP`` / ``# TYPE`` headers, and writing it
directly keeps TPU images dependency-free (the same stance as the stdlib
HTTP server and the MLflow-schema sqlite store).
"""

from __future__ import annotations

import re
from typing import Optional

from coda_tpu.telemetry.registry import Registry, get_registry

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _name(prefix: str, name: str) -> str:
    n = f"{prefix}_{name}" if prefix else name
    n = _NAME_OK.sub("_", n)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    v = float(value)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _line(name: str, labels: dict, value: float) -> str:
    if labels:
        lab = ",".join(f'{_NAME_OK.sub("_", str(k))}="{_escape(v)}"'
                       for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def _family(out: list, name: str, kind: str, help: str,
            samples: list) -> None:
    if help:
        out.append(f"# HELP {name} {_escape(help)}")
    out.append(f"# TYPE {name} {kind}")
    for labels, value in samples:
        out.append(_line(name, labels, value))


def _exemplar_family(out: list, name: str, help: str,
                     samples: list) -> None:
    """A gauge family whose samples carry OpenMetrics exemplars:
    ``name{labels} value # {trace_id="..."} value`` — the one-hop join
    from a latency outlier on ``/metrics`` to its distributed trace.
    ``samples`` is ``[(labels, {"value_s": float, "trace_id": str})]``."""
    if help:
        out.append(f"# HELP {name} {_escape(help)}")
    out.append(f"# TYPE {name} gauge")
    for labels, ex in samples:
        v = float(ex["value_s"])
        out.append(_line(name, labels, v)
                   + f' # {{trace_id="{_escape(ex["trace_id"])}"}} '
                   + _fmt(v))


def render(registry: Optional[Registry] = None, serve_metrics=None,
           prefix: str = "coda") -> str:
    """The registry (+ optional ServeMetrics snapshot) as exposition text."""
    out: list[str] = []
    reg = registry if registry is not None else get_registry()
    for m in reg.collect():
        _family(out, _name(prefix, m.name), m.kind, m.help, m.samples())
    if serve_metrics is not None:
        _render_serve(out, serve_metrics.snapshot(), prefix)
    return "\n".join(out) + "\n"


# (snapshot key, metric suffix, kind, help) — counters keep their
# monotonic-total names, distribution means/maxes surface as gauges
_SERVE_SCALARS = [
    ("uptime_s", "serve_uptime_seconds", "gauge",
     "Seconds since the serve metrics baseline (monotonic clock)"),
    ("dispatches", "serve_dispatches_total", "counter",
     "Compiled slab-step dispatches"),
    ("requests", "serve_requests_total", "counter",
     "Requests served across all dispatches"),
    ("sessions_opened", "serve_sessions_opened_total", "counter",
     "Sessions admitted"),
    ("sessions_closed", "serve_sessions_closed_total", "counter",
     "Sessions closed"),
    ("sessions_rejected", "serve_sessions_rejected_total", "counter",
     "Sessions refused by admission control (slab full / draining)"),
    ("requests_rejected", "serve_requests_rejected_total", "counter",
     "Requests refused (draining / unknown session / stale item)"),
    ("fencing_rejections", "serve_fencing_rejections_total", "counter",
     "Stale-epoch verbs this replica refused (the ownership fence held)"),
    ("max_occupancy", "serve_max_occupancy", "gauge",
     "Most requests ever served by one dispatch"),
    # tiered posterior state (serve/tiering.py)
    ("demotions", "serve_demotions_total", "counter",
     "Sessions demoted hot -> warm (slab slot freed, payload in host RAM)"),
    ("hibernates", "serve_hibernates_total", "counter",
     "Sessions hibernated warm -> cold (payload spilled to disk)"),
    ("peer_pages", "serve_peer_pages_total", "counter",
     "Warm sessions paged to a less-loaded peer replica instead of disk"),
    ("wakes", "serve_wakes_total", "counter",
     "Non-resident sessions transparently woken back onto the slab"),
    ("wakes_from_warm", "serve_wakes_from_warm_total", "counter",
     "Wakes served from the host-RAM warm tier"),
    ("wakes_from_cold", "serve_wakes_from_cold_total", "counter",
     "Wakes served from the on-disk cold tier"),
    ("wake_failures", "serve_wake_failures_total", "counter",
     "Wakes that raised (payload re-parked, session still reachable)"),
    ("mean_occupancy", "serve_mean_occupancy", "gauge",
     "Mean requests per dispatch over the recent ring"),
    ("mean_queue_depth", "serve_mean_queue_depth", "gauge",
     "Mean queue depth at tick start over the recent ring"),
    ("ring_capacity", "serve_ring_capacity", "gauge",
     "Capacity of each metrics ring (fill == capacity means wrapped)"),
    # contract-gated EIG surrogate (--eig-scorer surrogate:k buckets):
    # absent (not zero) on servers with no surrogate bucket. GAUGES, not
    # counters: the values are sums over LIVE slots of the slab-carried
    # fit state, and a session closing / demoting / migrating away takes
    # its slot's contribution with it — a decreasing "_total" would make
    # Prometheus rate() fabricate counter-reset spikes
    ("surrogate_rounds", "serve_surrogate_rounds", "gauge",
     "Rounds scored by the surrogate rung, summed over live slots "
     "(decreases when sessions close/demote/migrate)"),
    ("surrogate_fallbacks", "serve_surrogate_fallbacks", "gauge",
     "Surrogate rounds that fell back to the full exact pass on a "
     "violated contract, summed over live slots"),
    ("surrogate_fit_refreshes", "serve_surrogate_fit_refreshes", "gauge",
     "Surrogate ridge-fit refolds (normal-equation updates + re-solves), "
     "summed over live slots"),
    ("surrogate_contract_margin", "serve_surrogate_contract_margin",
     "gauge",
     "Worst escape-gate margin across live slots (best refreshed exact "
     "score minus best unrefreshed prediction; healthy > 0)"),
    # cross-session surrogate prior pool (--surrogate-prior pool): absent
    # (not zero) under the default 'off'. The warmup/rejection pair are
    # live-slot sums of slab-carried counters (same decrease-on-close
    # semantics as the surrogate gauges above); sessions_contributed is
    # pool state and only ever grows, but stays a gauge so the family
    # keeps one scrape semantics
    ("prior_sessions_contributed", "serve_prior_sessions_contributed",
     "gauge",
     "Sessions whose surrogate fit statistics were folded into the "
     "cross-session prior pool"),
    ("prior_warmup_rounds_skipped", "serve_prior_warmup_rounds_skipped",
     "gauge",
     "Exact warmup rounds the pool prior credited to live sessions "
     "(summed over live slots; decreases when sessions close/demote)"),
    ("prior_gate_rejections", "serve_prior_gate_rejections", "gauge",
     "Trust-gate fallbacks fired inside a prior-credited warmup window, "
     "summed over live slots (a transferring-badly prior shows up here)"),
    ("prior_pools", "serve_prior_pools", "gauge",
     "Distinct (task, pool-fingerprint) priors this replica holds"),
    ("prior_rounds_pooled", "serve_prior_rounds_pooled", "gauge",
     "Decay-weighted audited rounds aggregated across all pool priors"),
    ("prior_pool_staleness_seconds", "serve_prior_pool_staleness_seconds",
     "gauge",
     "Age of the LEAST recently refreshed prior pool (seconds since its "
     "last statistic fold) — the learned-decay sensor's staleness axis"),
]

# spill store v3 evidence (serve/spill.py, nested under snapshot["spill"]):
# absent without --tier-spill-dir
_SERVE_SPILL = [
    ("entries", "serve_spill_entries", "gauge",
     "Live hibernated payloads in the spill store"),
    ("segments", "serve_spill_segments", "gauge",
     "Sharded segment files currently on disk"),
    ("live_bytes", "serve_spill_live_bytes", "gauge",
     "Bytes of live frames across all segments"),
    ("log_bytes", "serve_spill_log_bytes", "gauge",
     "Total bytes across all segment files"),
    ("garbage_bytes", "serve_spill_garbage_bytes", "gauge",
     "Bytes of superseded/tombstoned frames awaiting compaction"),
    ("segment_compactions", "serve_spill_segment_compactions_total",
     "counter",
     "Per-segment compactions (live frames copied forward, file "
     "reclaimed) — never stop-the-world"),
    ("put_errors", "serve_spill_put_errors_total", "counter",
     "Spill appends that failed (payload kept warm instead)"),
    ("startup_scan_frames", "serve_spill_startup_scan_frames", "gauge",
     "Frames the last startup had to scan past the persisted index "
     "(0 = pure O(index) startup)"),
]

# decision-quality plane (telemetry/quality.py, nested under
# snapshot["quality"]): absent (not zero) with --no-quality. Each entry
# is (suffix, kind, help, extract) where extract(quality_snapshot)
# returns [(extra_labels, value)] — shared by the single-replica and
# fleet render paths (the fleet path merges a replica label in).

def _q_audit(key):
    def extract(q):
        v = (q.get("audit") or {}).get(key)
        return [] if v is None else [({}, v)]
    return extract


def _q_scalar(key):
    def extract(q):
        v = q.get(key)
        return [] if v is None else [({}, v)]
    return extract


def _q_calibration(key):
    def extract(q):
        return [({"task": task}, cal[key])
                for task, cal in sorted((q.get("calibration") or {}).items())
                if cal.get(key) is not None]
    return extract


def _q_drift(key):
    def extract(q):
        out = []
        for name, det in sorted((q.get("drift") or {}).items()):
            # absent, not zero: a detector whose signal never fed (e.g.
            # surrogate gate pressure on an exact-scorer server) exports
            # no series — families only exist where the signal runs
            if not det.get("observations"):
                continue
            v = det.get(key)
            if v is not None:
                out.append(({"detector": name},
                            float(v) if not isinstance(v, bool)
                            else (1.0 if v else 0.0)))
        return out
    return extract


_SERVE_QUALITY = [
    ("quality_audits_total", "counter",
     "Closed sessions the shadow auditor bitwise-re-replayed",
     _q_audit("audits_total")),
    ("quality_audits_skipped_total", "counter",
     "Shadow audits skipped (scratch slab full / replay setup failure)",
     _q_audit("audits_skipped")),
    ("quality_audit_rounds_verified_total", "counter",
     "Recorded decision rounds bitwise-verified by shadow replays",
     _q_audit("rounds_verified")),
    ("quality_audit_divergences_total", "counter",
     "Shadow replays that bitwise-diverged from the recorded stream "
     "(must stay 0 on a healthy fleet)",
     _q_audit("divergences_total")),
    ("quality_audit_divergences_recent", "gauge",
     "Divergences inside the recent attribution window",
     _q_audit("divergences_recent")),
    ("quality_audit_tampered_total", "counter",
     "Audits whose stream copy was deliberately ulp-tampered by fault "
     "injection (each must show up as a divergence)",
     _q_audit("tampered_total")),
    ("quality_audit_prior_gap", "gauge",
     "Seeded-vs-cold shadow-replay decision gap (EWMA fraction of "
     "warmup rounds where the pool prior changed the pick; a healthy "
     "prior keeps this HIGH — it is actually steering)",
     _q_audit("prior_gap")),
    ("quality_audit_queue_drops_total", "counter",
     "Audit candidates dropped because the audit queue was full",
     _q_scalar("audit_queue_drops")),
    ("quality_pre_dispatch_errors_total", "counter",
     "Calibration pre-dispatch reads that raised (decision math is "
     "never affected; the round just goes unobserved)",
     _q_scalar("pre_dispatch_errors")),
    ("quality_calibration_rounds", "gauge",
     "Labeled rounds folded into the task's calibration accumulators",
     _q_calibration("n")),
    ("quality_calibration_ece", "gauge",
     "Streaming expected calibration error of the served posterior's "
     "predicted-label confidence, per task",
     _q_calibration("ece")),
    ("quality_calibration_brier", "gauge",
     "Streaming Brier score of the served posterior's predicted-label "
     "confidence, per task",
     _q_calibration("brier")),
    ("quality_drift_statistic", "gauge",
     "Current drift-detector statistic (CUSUM s / Page-Hinkley m-min)",
     _q_drift("statistic")),
    ("quality_drift_firing", "gauge",
     "Whether the drift detector is currently firing (0/1)",
     _q_drift("firing")),
    ("quality_drift_fired_total", "counter",
     "Drift-detector fire transitions since start",
     _q_drift("fired_total")),
]


_SERVE_SUMMARIES = [
    ("dispatch_latency", "serve_dispatch_latency_seconds", "dispatches",
     "Slab-step dispatch seconds over the recent ring"),
    ("request_latency", "serve_request_latency_seconds", "requests",
     "Submit-to-result request seconds over the recent ring"),
    ("queue_wait", "serve_queue_wait_seconds", "requests",
     "Submit-to-tick-start queue wait seconds over the recent ring"),
    ("step_latency", "serve_step_latency_seconds", "dispatches",
     "Compiled slab-step execution seconds over the recent ring"),
    ("wake_latency", "serve_wake_latency_seconds", "wakes",
     "Non-resident session wake seconds over the recent ring"),
]

# warm-pool evidence: (warm_pool snapshot key, metric suffix, kind, help)
_SERVE_WARM = [
    ("size", "serve_warm_pool_size", "gauge",
     "AOT-precompiled executables in the warm pool"),
    ("warm_s", "serve_warm_pool_seconds", "gauge",
     "Wall seconds the warm-up pass took"),
    ("hits", "serve_warm_pool_hits_total", "counter",
     "Dispatches served by an AOT-precompiled executable"),
    ("misses", "serve_warm_pool_misses_total", "counter",
     "Dispatches that fell back to lazy jit compilation"),
]


# -- exposition lint ---------------------------------------------------------

_METRIC_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
# one sample line: name{labels} value — labels quoted, escapes resolved by
# the tokenizer below, value a float or NaN/+Inf/-Inf; optionally followed
# by an OpenMetrics exemplar ``# {labels} value [timestamp]``. The labels
# group is non-greedy so a greedy match cannot swallow the exemplar's
# braces into the sample's label body (backtracking still recovers label
# values that legitimately contain ``}`` or ``# {``).
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*?)\})?"
    r" (?P<value>NaN|[+-]Inf|[+-]?[0-9][0-9.eE+-]*)"
    r"(?P<exemplar> # \{(?P<elabels>.*)\}"
    r" (?P<evalue>NaN|[+-]Inf|[+-]?[0-9][0-9.eE+-]*)"
    r"(?: (?P<ets>[0-9][0-9.eE+-]*))?)?$")
_LABEL_PAIR = re.compile(
    r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\\n]|\\["\\n])*)"')
# the WHOLE label body must be comma-separated pairs (an optional trailing
# comma is legal exposition) — substring matching alone would tolerate
# missing separators like k1="a"k2="b"
_LABELS_BODY = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*,?$')


def lint(text: str) -> list[str]:
    """Violations of the v0.0.4 text-exposition contract (empty = clean).

    The checks a scraping Prometheus would actually choke or mis-ingest
    on: malformed sample lines, unescaped label values or missing label
    separators, duplicate series (same name + label set twice), a HELP
    after its family's TYPE, a family re-opened after other families
    interleaved (duplicate TYPE), samples with no TYPE, bad metric/label
    names, and values that are not valid floats (NaN/±Inf must use the
    canonical spellings). Summary ``_count``/``_sum`` suffixed samples
    belong to their base family. OpenMetrics exemplars
    (``# {trace_id="..."} value``) are validated like sample labels and
    are only legal on gauge and histogram families — a counter or summary
    exemplar is how a hand-rolled renderer silently breaks OpenMetrics
    parsers, so it lints.
    """
    out: list[str] = []
    typed: dict[str, str] = {}       # family -> kind
    helped: set[str] = set()
    closed: set[str] = set()         # families a later line may not reopen
    series: set[tuple] = set()       # (name, canonical labels) seen
    current: str = ""

    def _family_of(name: str) -> str:
        base = name
        for suffix in ("_count", "_sum", "_bucket"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed:
                base = name[: -len(suffix)]
        return base

    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3:
                out.append(f"line {i}: malformed HELP")
                continue
            name = parts[2]
            if name in helped:
                out.append(f"line {i}: duplicate HELP for {name}")
            if name in typed:
                out.append(f"line {i}: HELP for {name} after its TYPE")
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4:
                out.append(f"line {i}: malformed TYPE")
                continue
            name, kind = parts[2], parts[3]
            if not _METRIC_NAME.match(name):
                out.append(f"line {i}: bad metric name {name!r}")
            if kind not in ("counter", "gauge", "summary", "histogram",
                            "untyped"):
                out.append(f"line {i}: unknown TYPE kind {kind!r}")
            if name in typed:
                out.append(f"line {i}: duplicate TYPE for {name}")
            if name in closed:
                out.append(f"line {i}: family {name} reopened after other "
                           "families (non-contiguous)")
            if current and current != name:
                closed.add(current)
            typed[name] = kind
            current = name
            continue
        if line.startswith("#"):
            continue  # comments are legal anywhere
        m = _SAMPLE.match(line)
        if not m:
            out.append(f"line {i}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        fam = _family_of(name)
        if fam not in typed:
            out.append(f"line {i}: sample {name} has no TYPE header")
        elif fam != current:
            out.append(f"line {i}: sample {name} outside its family block")
        labels = m.group("labels")
        pairs: list = []
        if labels is not None:
            if not (labels == "" or _LABELS_BODY.match(labels)):
                out.append(f"line {i}: malformed/unescaped labels "
                           f"{labels!r} (pairs must be comma-separated "
                           "with escaped quoted values)")
            else:
                seen = []
                for lm in _LABEL_PAIR.finditer(labels):
                    if lm.group("k") in seen:
                        out.append(f"line {i}: duplicate label "
                                   f"{lm.group('k')!r}")
                    seen.append(lm.group("k"))
                    pairs.append((lm.group("k"), lm.group("v")))
        if m.group("exemplar"):
            kind = typed.get(fam)
            if kind not in ("gauge", "histogram"):
                out.append(f"line {i}: exemplar on {kind or 'untyped'} "
                           f"family {fam} (exemplars are only legal on "
                           "gauge/histogram samples)")
            elabels = m.group("elabels")
            if elabels and not _LABELS_BODY.match(elabels):
                out.append(f"line {i}: malformed exemplar labels "
                           f"{elabels!r}")
        key = (name, tuple(sorted(pairs)))
        if key in series:
            out.append(f"line {i}: duplicate series {name}"
                       f"{{{dict(pairs)}}} (same name + label set "
                       "emitted twice)")
        series.add(key)
        val = m.group("value")
        if val not in ("NaN", "+Inf", "-Inf"):
            try:
                float(val)
            except ValueError:
                out.append(f"line {i}: bad value {val!r}")
    return out


def render_fleet(replica_snaps: dict, registry: Optional[Registry] = None,
                 router_stats: Optional[dict] = None,
                 prefix: str = "coda") -> str:
    """The fleet's merged exposition: each serve family rendered ONCE
    with a ``replica`` label per sample (families stay contiguous, so
    the output is :func:`lint`-clean), plus the router's own routing/
    migration counters. This is what keeps fleet observability a single
    scrape instead of a per-replica curl loop.

    ``replica_snaps`` maps replica id -> its ``ServeMetrics.snapshot()``
    dict (the ``/stats`` payload — handle-type agnostic, so HTTP and
    in-process replicas merge identically)."""
    out: list[str] = []
    reg = registry if registry is not None else get_registry()
    for m in reg.collect():
        _family(out, _name(prefix, m.name), m.kind, m.help, m.samples())
    if router_stats is not None:
        rt = router_stats
        counters = rt.get("counters") or {}
        for key, help in (
                ("requests_routed", "Requests the router forwarded"),
                ("reroutes", "Requests re-routed after an off-owner find"),
                ("migrations", "Sessions drain-and-migrated between "
                               "replicas (each digest-verified)"),
                ("migration_failures", "Migrations that failed and were "
                                       "restored to their source"),
                ("sessions_dropped", "Sessions lost in a failed migration "
                                     "(must stay 0)"),
                ("evictions", "Replicas evicted from routing by health"),
                ("rejoins", "Replicas re-admitted to routing by health"),
                ("rebalances", "Topology-change rebalance passes"),
                ("fence_failures", "Migration commits whose source fence "
                                   "did not land (stale copy defended by "
                                   "the epoch stamp until re-fenced)"),
        ):
            if key in counters:
                _family(out, _name(prefix, f"router_{key}_total"),
                        "counter", help, [({}, counters[key])])
        # the fleet-chaos families (ISSUE 14): fencing rejections the
        # router absorbed, journal replays, per-replica transport retries
        # and breaker state — named exactly as the runbooks grep for them
        if "fencing_rejections" in counters:
            _family(out, _name(prefix, "fencing_rejections_total"),
                    "counter",
                    "Stale-epoch verbs refused fleet-wide (each one a "
                    "prevented split-brain double-apply)",
                    [({}, counters["fencing_rejections"])])
        if "journal_replays" in counters:
            _family(out, _name(prefix, "migration_journal_replays_total"),
                    "counter",
                    "In-doubt migrations resolved from the journal after "
                    "a restart (finalized or restored)",
                    [({}, counters["journal_replays"])])
        retries = rt.get("transport_retries") or {}
        if retries:
            _family(out, _name(prefix, "transport_retries_total"),
                    "counter",
                    "Replica-call transport retries (idempotent verbs "
                    "only, per-replica budgeted)",
                    [({"replica": rid}, n)
                     for rid, n in sorted(retries.items())])
        breakers = rt.get("breakers") or {}
        if breakers:
            order = {"closed": 0, "half_open": 1, "open": 2}
            _family(out, _name(prefix, "replica_breaker_state"),
                    "gauge",
                    "Per-replica transport circuit breaker "
                    "(0=closed, 1=half-open, 2=open)",
                    [({"replica": rid},
                      order.get(b.get("state"), 0))
                     for rid, b in sorted(breakers.items())])
        routed = rt.get("requests_to") or {}
        if routed:
            _family(out, _name(prefix, "router_requests_to_replica_total"),
                    "counter", "Requests forwarded per replica",
                    [({"replica": rid}, n)
                     for rid, n in sorted(routed.items())])
        routable = rt.get("routable")
        if routable is not None:
            _family(out, _name(prefix, "router_routable_replicas"),
                    "gauge", "Replicas currently in the routing set",
                    [({}, len(routable))])
    snaps = {rid: s for rid, s in sorted(replica_snaps.items())
             if isinstance(s, dict) and "error" not in s}
    for key, suffix, kind, help in _SERVE_SCALARS:
        samples = [({"replica": rid}, s[key])
                   for rid, s in snaps.items() if s.get(key) is not None]
        if samples:
            _family(out, _name(prefix, suffix), kind, help, samples)
    for key, suffix, kind, help in _SERVE_WARM:
        samples = [({"replica": rid}, (s.get("warm_pool") or {}).get(key))
                   for rid, s in snaps.items()
                   if (s.get("warm_pool") or {}).get(key) is not None]
        if samples:
            _family(out, _name(prefix, suffix), kind, help, samples)
    for tier in ("hot", "warm", "cold"):
        samples = [({"replica": rid}, (s.get("tiers") or {}).get(tier))
                   for rid, s in snaps.items()
                   if (s.get("tiers") or {}).get(tier) is not None]
        if samples:
            _family(out, _name(prefix, f"serve_sessions_{tier}"), "gauge",
                    f"Open sessions currently in the {tier} tier",
                    samples)
    for key, suffix, kind, help in _SERVE_SPILL:
        samples = [({"replica": rid}, (s.get("spill") or {}).get(key))
                   for rid, s in snaps.items()
                   if (s.get("spill") or {}).get(key) is not None]
        if samples:
            _family(out, _name(prefix, suffix), kind, help, samples)
    for suffix, kind, help, extract in _SERVE_QUALITY:
        samples = []
        for rid, s in snaps.items():
            quality = s.get("quality")
            if not isinstance(quality, dict):
                continue
            for extra, v in extract(quality):
                labels = {"replica": rid}
                labels.update(extra)
                samples.append((labels, v))
        if samples:
            _family(out, _name(prefix, suffix), kind, help, samples)
    samples = []
    for rid, s in snaps.items():
        ages = (s.get("prior_pool_ages_seconds")
                or (s.get("prior_pool") or {}).get("pool_ages_seconds")
                or {})
        samples.extend(({"pool": k, "replica": rid}, v)
                       for k, v in sorted(ages.items()))
    if samples:
        _family(out, _name(prefix, "serve_prior_pool_age_seconds"),
                "gauge",
                "Seconds since each prior pool's last statistic fold",
                samples)
    samples = [({"replica": rid, "ring": ring}, ex)
               for rid, s in snaps.items()
               for ring, ex in sorted((s.get("exemplars") or {}).items())
               if ex and ex.get("trace_id")]
    if samples:
        _exemplar_family(
            out, _name(prefix, "serve_latency_outlier_seconds"),
            "Latest p99-bucket latency outlier per replica and ring; the "
            "exemplar's trace_id joins it to its stitched distributed "
            "trace", samples)
    for key, suffix, count_key, help in _SERVE_SUMMARIES:
        name = _name(prefix, suffix)
        samples = []
        counts = []
        for rid, s in snaps.items():
            q = s.get(key) or {}
            for qk, quantile in (("p50_ms", "0.5"), ("p99_ms", "0.99")):
                if q.get(qk) is not None:
                    samples.append(({"quantile": quantile,
                                     "replica": rid}, q[qk] / 1e3))
            if q.get("p50_ms") is not None:
                counts.append(({"replica": rid}, s.get(count_key, 0)))
        if not samples:
            continue
        _family(out, name, "summary", help, samples)
        for labels, n in counts:
            out.append(_line(name + "_count", labels, n))
    return "\n".join(out) + "\n"


def _render_serve(out: list, snap: dict, prefix: str) -> None:
    for key, suffix, kind, help in _SERVE_SCALARS:
        v = snap.get(key)
        if v is not None:
            _family(out, _name(prefix, suffix), kind, help, [({}, v)])
    warm = snap.get("warm_pool") or {}
    for key, suffix, kind, help in _SERVE_WARM:
        v = warm.get(key)
        if v is not None:
            _family(out, _name(prefix, suffix), kind, help, [({}, v)])
    tiers = snap.get("tiers") or {}
    for tier in ("hot", "warm", "cold"):
        if tier in tiers:
            _family(out, _name(prefix, f"serve_sessions_{tier}"), "gauge",
                    f"Open sessions currently in the {tier} tier",
                    [({}, tiers[tier])])
    spill = snap.get("spill") or {}
    for key, suffix, kind, help in _SERVE_SPILL:
        v = spill.get(key)
        if v is not None:
            _family(out, _name(prefix, suffix), kind, help, [({}, v)])
    quality = snap.get("quality")
    if isinstance(quality, dict):
        for suffix, kind, help, extract in _SERVE_QUALITY:
            samples = extract(quality)
            if samples:
                _family(out, _name(prefix, suffix), kind, help, samples)
    ages = (snap.get("prior_pool_ages_seconds")
            or (snap.get("prior_pool") or {}).get("pool_ages_seconds")
            or {})
    if ages:
        _family(out, _name(prefix, "serve_prior_pool_age_seconds"),
                "gauge",
                "Seconds since each prior pool's last statistic fold",
                [({"pool": k}, v) for k, v in sorted(ages.items())])
    fills = snap.get("ring_fill") or {}
    if fills:
        _family(out, _name(prefix, "serve_ring_fill"), "gauge",
                "Events currently held in a metrics ring",
                [({"ring": k}, n) for k, n in sorted(fills.items())])
    exemplars = snap.get("exemplars") or {}
    samples = [({"ring": ring}, ex)
               for ring, ex in sorted(exemplars.items())
               if ex and ex.get("trace_id")]
    if samples:
        _exemplar_family(
            out, _name(prefix, "serve_latency_outlier_seconds"),
            "Latest p99-bucket latency outlier per ring; the exemplar's "
            "trace_id joins it to its stitched distributed trace",
            samples)
    for key, suffix, count_key, help in _SERVE_SUMMARIES:
        q = snap.get(key) or {}
        name = _name(prefix, suffix)
        samples = []
        for qk, quantile in (("p50_ms", "0.5"), ("p99_ms", "0.99")):
            if q.get(qk) is not None:
                samples.append(({"quantile": quantile}, q[qk] / 1e3))
        if not samples:
            continue
        _family(out, name, "summary", help, samples)
        out.append(_line(name + "_count", {}, snap.get(count_key, 0)))
