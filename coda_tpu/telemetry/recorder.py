"""Decision flight recorder: per-round provenance records on disk.

CODA's output is a sequence of irreversible decisions — each round picks one
point, consumes one oracle label, and updates the posterior — so when two
runs disagree (bf16 vs exact caches, pallas vs XLA, sharded vs unsharded,
approx vs exact entropy) the question that matters is *which round first
diverged and in what quantity*. This module is the capture half of that
story; ``coda_tpu/engine/replay.py`` is the verify/triage half.

What gets captured, per labeling round (``engine/loop.py`` emits it as
auxiliary ``lax.scan`` outputs — device-side, harvested once per run,
O(rounds·k) host traffic, no per-round sync):

  * chosen index, oracle label, selection probability (the decision);
  * top-k acquisition scores + indices, the chosen score, and the
    argmax runner-up gap (the *why*, and how contested it was);
  * a posterior P(best) digest — max + entropy in bits — for methods that
    expose one (CODA, ModelPicker);
  * the round's PRNG key counter words (so replay reconstructs the exact
    randomness even if key derivation ever changes).

Plus one run-level **environment fingerprint**: backend, jax/jaxlib
versions, device kind, the numerics knobs (``eig_entropy``, cache dtype,
precision, ...), a dataset digest, and ``jax_threefry_partitionable`` —
every axis along which the PR 4 threefry/GSPMD parity bug (NOTES_r07.md)
could have been spotted mechanically.

On-disk layout of one run record (validated by
``scripts/check_record_schema.py``)::

    <dir>/record.json   # schema_version, fingerprint, run config, shapes
    <dir>/rounds.npz    # the per-seed x per-round arrays (REQUIRED_ARRAYS)

Batch runs write one record per run (``cli.py --record-dir``); the suite
writes per-(family, method) record streams (one record per task under
``<root>/<family>__<method>/<task>/``); the serving layer streams per-
session JSONL rows (:class:`SessionRecorder`) since an interactive session
has no known end. Recorder activity registers counters/gauges with the
process-wide telemetry registry, so ``records_written_total`` /
``replay_verified_total`` surface on ``/metrics`` next to recompiles and
HBM watermarks.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# bump on ANY field change; check_record_schema.py fails unversioned or
# field-drifted records so downstream triage never misreads old captures.
# Batch records (record.json + rounds.npz) and serving-session JSONL
# streams version INDEPENDENTLY — a stream-only field change must not
# invalidate every previously captured batch record.
# v2: batched acquisition (--acq-batch q): meta gained ``acq_batch`` and,
# for q > 1, the per-round decision arrays chosen_idx / true_class /
# select_prob carry a trailing (q,) axis (one entry per oracle answer of
# the round). q = 1 records are v1's arrays exactly — v1 records load as
# acq_batch=1 (the committed r12 captures stay replayable).
# v3: the contract-gated EIG surrogate (--eig-scorer surrogate:k): rounds
# gained the per-round ``surrogate_fallback`` bool array (did the round's
# scorer fall back to the full exact pass on a violated contract — the
# stream evidence behind the committed fallback-rate bound), and
# ``eig_scorer`` joined KNOB_FIELDS. v1/v2 records load unchanged (the
# array is absent there and replay comparisons skip quantities either
# side lacks), so the committed r12/r14 captures stay replayable.
# v4: the crowd oracle (--oracle-noise): oracle_noise /
# oracle_annotators / oracle_reliability joined KNOB_FIELDS, and crowd
# runs OPTIONALLY carry the per-round ``oracle_label`` (ground truth of
# the chosen point) and ``label_weight`` (the reliability weight the
# update applied) arrays — OPTIONAL_ARRAYS, validated only when present,
# so clean and pre-crowd records carry nothing new and still compare
# bitwise (the r12-r16 captures stay replayable).
RECORD_SCHEMA_VERSION = 4
SUPPORTED_RECORD_VERSIONS = (1, 2, 3, 4)
# v2: session-stream rows gained request_id + pbest_max/pbest_entropy
# (the in-step posterior digest) and the session_close marker kind — a v1
# stream replayed by this build would misreport the absent digests as a
# divergence, so the version gate rejects it with the real reason instead.
# v3: batch-label sessions (POST /session/{id}/labels): rows'
# labeled_idx/label/prob and next_idx/next_prob may be q-wide LISTS, and
# the session meta carries ``acq_batch`` — a v2 reader would replay a
# batch row as a single mis-shaped label, so v3 streams gate out old
# readers. The other direction is SAFE at q=1: v3 only ADDS fields there,
# so a v2 stream replays bitwise on an acq_batch=1 server — restore
# accepts it (a deploy must not discard every in-flight session) and
# treats its missing ``acq_batch`` meta as 1; a v2 stream on a q>1
# server is rejected with the real acq_batch-mismatch reason.
# v4: asynchronous oracle answers (POST /session/{id}/answer): streams may
# carry ``answer_park`` rows — a per-slot crowd answer parked until the
# whole q-wide round is filled — so a crash between parking and the
# round's dispatch replays with 0 lost labels. v3 readers would drop the
# parked answers on restore, so v4 streams gate them out; v2/v3 streams
# (no park rows possible) still restore here unchanged.
# Additive-optional row fields (NO version bump — replay compares only
# the decision quantities, and every reader tolerates extra keys, so the
# bitwise pin on existing keys is preserved):
#   * ``trace_id`` (r19) — the serving trace the row's request rode;
#     absent (not null) when untraced.
#   * ``pred_label_prob`` (r20) — the probability the session's consensus
#     posterior pi_hat assigned to the realized oracle label, read
#     pre-update by the decision-quality plane (telemetry/quality.py);
#     absent with ``--no-quality``, so quality-off streams stay bitwise
#     identical to pre-quality streams.
SESSION_SCHEMA_VERSION = 4
SUPPORTED_SESSION_VERSIONS = (2, 3, 4)

# the documented cross-backend score contract: pallas kernels vs the XLA
# lowering agree on EIG scores to the MEASURED 2.34e-4 at the headline shape
# (ARCHITECTURE.md §2, fusedcompute_row_max_abs_diff); replay comparisons
# across backends/knobs use this bound, same-backend replays demand bitwise
CROSS_BACKEND_SCORE_TOL = 2.34e-4

# every array a rounds.npz must carry: name -> (dtype kind, ndim with the
# leading seed axis) at acq_batch = 1. trace_k (the k of the top-k
# columns) lives in meta; :func:`required_arrays` adjusts the ranks for
# q-wide (acq_batch > 1) records.
REQUIRED_ARRAYS = {
    "chosen_idx": ("i", 2),        # (S, T)
    "true_class": ("i", 2),        # (S, T)
    "best_model": ("i", 2),        # (S, T)
    "regret": ("f", 2),            # (S, T)
    "cumulative_regret": ("f", 2),  # (S, T)
    "select_prob": ("f", 2),       # (S, T)
    "regret_at_0": ("f", 1),       # (S,)
    "stochastic": ("b", 1),        # (S,)
    "round_key": ("u", 3),         # (S, T, 2)
    "topk_idx": ("i", 3),          # (S, T, k)
    "topk_score": ("f", 3),        # (S, T, k)
    "chosen_score": ("f", 2),      # (S, T)
    "runner_up_gap": ("f", 2),     # (S, T)
    "pbest_max": ("f", 2),         # (S, T)
    "pbest_entropy": ("f", 2),     # (S, T)
    "root_key": ("u", 2),          # (S, 2)
    "init_key": ("u", 2),          # (S, 2)
    "prior_key": ("u", 2),         # (S, 2)
}

REQUIRED_META = ("schema_version", "fingerprint", "run", "trace_k",
                 "seeds", "rounds")

# the per-round decision arrays that grow a trailing (q,) axis under
# batched acquisition
_BATCH_ARRAYS = ("chosen_idx", "true_class", "select_prob")

# arrays that exist only from a given schema version on
_VERSIONED_ARRAYS = {
    "surrogate_fallback": (3, ("b", 2)),   # (S, T) — v3's addition
}

# arrays a record MAY carry but need not (validated only when present):
# crowd-oracle runs record what the noisy crowd answered and how much the
# reliability posterior trusted it; clean runs carry neither, so their
# rounds.npz stays byte-identical to a pre-v4 capture. Both grow the
# trailing (q,) axis under batched acquisition, like _BATCH_ARRAYS.
_OPTIONAL_ARRAYS = {
    "oracle_label": ("i", 2),   # (S, T) — the aggregated crowd answer
    "label_weight": ("f", 2),   # (S, T) — the applied reliability weight
}


def optional_arrays(acq_batch: int = 1) -> dict:
    """The OPTIONAL per-round arrays (crowd-oracle runs) at a record's
    ``acq_batch``: same q-axis rule as the required decision arrays."""
    out = dict(_OPTIONAL_ARRAYS)
    if acq_batch <= 1:
        return out
    return {name: (kind, ndim + 1) for name, (kind, ndim) in out.items()}


def required_arrays(acq_batch: int = 1,
                    schema_version: int = RECORD_SCHEMA_VERSION) -> dict:
    """The REQUIRED_ARRAYS spec for a record's ``acq_batch`` and schema
    version: at q > 1 the decision arrays are (S, T, q) instead of
    (S, T); v3 records additionally carry ``surrogate_fallback``."""
    out = dict(REQUIRED_ARRAYS)
    for name, (since, spec) in _VERSIONED_ARRAYS.items():
        if schema_version >= since:
            out[name] = spec
    if acq_batch <= 1:
        return out
    for name in _BATCH_ARRAYS:
        kind, ndim = out[name]
        out[name] = (kind, ndim + 1)
    return out

# the knob subset of an argparse namespace worth fingerprinting: every flag
# that can change the decision trace (numerics, acquisition, RNG layout)
KNOB_FIELDS = (
    "method", "loss", "iters", "seeds", "alpha", "learning_rate",
    "multiplier", "prefilter_n", "no_diag_prior", "q", "epsilon",
    "eig_chunk", "eig_mode", "eig_backend", "eig_precision",
    "eig_cache_dtype", "eig_refresh", "eig_entropy", "posterior",
    "eig_pbest", "eig_scorer", "pi_update", "mesh", "acq_batch",
    "oracle_noise", "oracle_annotators", "oracle_reliability",
    # v4 (PR 18): the cross-session surrogate prior mode + the digest of
    # the applied pool prior (serve/priors.py) — the digest, not the
    # statistics, is the knob: two runs seeded from different pools are
    # different environments and must not compare bitwise
    "surrogate_prior", "surrogate_prior_digest",
)


def _counters(registry=None):
    from coda_tpu.telemetry.registry import get_registry

    reg = registry if registry is not None else get_registry()
    return reg


def dataset_digest(preds, labels=None, max_bytes: int = 1 << 28) -> str:
    """Stable 16-hex digest of the prediction tensor (+ labels).

    Full-byte sha256 up to ``max_bytes`` per array; beyond that a strided
    ~16M-element subsample plus shape/dtype (DomainNet-scale tensors must
    not turn fingerprinting into a 10 GB hash pass). Good enough to catch
    swapped/retouched datasets, which is what replay needs."""
    h = hashlib.sha256()
    for arr in (preds, labels):
        if arr is None:
            continue
        a = np.asarray(arr)
        h.update(repr((a.shape, str(a.dtype))).encode())
        if a.nbytes <= max_bytes:
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            flat = a.reshape(-1)
            stride = max(1, flat.size // (1 << 24))
            h.update(np.ascontiguousarray(flat[::stride]).tobytes())
    return h.hexdigest()[:16]


def environment_fingerprint(dataset=None, knobs: Optional[dict] = None,
                            digest: Optional[str] = None) -> dict:
    """The run-level provenance block of a record.

    Captures every environment axis that has historically moved a decision
    trace: backend + device kind, jax/jaxlib versions, x64 and
    ``jax_threefry_partitionable`` (the NOTES_r07 GSPMD-parity switch),
    the numerics knobs, and a dataset digest."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", None)
    except Exception:
        jaxlib_version = None
    devs = jax.devices()
    fp = {
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
        "device_kind": devs[0].device_kind if devs else None,
        "n_devices": jax.device_count(),
        "threefry_partitionable": bool(
            jax.config.jax_threefry_partitionable),
        "x64": bool(jax.config.jax_enable_x64),
        "knobs": dict(knobs or {}),
    }
    ds = {}
    if dataset is not None:
        ds = {"name": getattr(dataset, "name", None),
              "shape": list(getattr(dataset, "shape", ()) or ())}
        if digest is None and getattr(dataset, "preds", None) is not None:
            digest = dataset_digest(dataset.preds,
                                    getattr(dataset, "labels", None))
    if digest is not None:
        ds["digest"] = digest
    fp["dataset"] = ds
    return fp


def knobs_from_args(args) -> dict:
    """The fingerprint-worthy knob subset of an argparse namespace."""
    out = {}
    for k in KNOB_FIELDS:
        v = getattr(args, k, None)
        if v is not None:
            out[k] = v
    return out


@dataclass
class RunRecord:
    """One recorded run: JSON meta + the per-seed/per-round arrays."""

    meta: dict
    arrays: dict = field(default_factory=dict)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_result(cls, result, aux, fingerprint: dict, run: dict,
                    extra_meta: Optional[dict] = None,
                    crowd=None) -> "RunRecord":
        """Build a record from an ``(ExperimentResult, RunTraceAux)`` pair
        (leading seed axis on both, as ``run_seeds_recorded`` returns).
        ``crowd`` is the optional ``CrowdAux`` of a crowd-oracle run —
        it adds the v4 OPTIONAL arrays; clean runs pass None and the
        record stays byte-identical to a pre-v4 capture."""
        arrays = {
            "chosen_idx": np.asarray(result.chosen_idx, np.int32),
            "true_class": np.asarray(result.true_class, np.int32),
            "best_model": np.asarray(result.best_model, np.int32),
            "regret": np.asarray(result.regret, np.float32),
            "cumulative_regret": np.asarray(result.cumulative_regret,
                                            np.float32),
            "select_prob": np.asarray(result.select_prob, np.float32),
            "regret_at_0": np.atleast_1d(
                np.asarray(result.regret_at_0, np.float32)),
            "stochastic": np.atleast_1d(np.asarray(result.stochastic, bool)),
            "round_key": np.asarray(aux.trace.round_key, np.uint32),
            "topk_idx": np.asarray(aux.trace.topk_idx, np.int32),
            "topk_score": np.asarray(aux.trace.topk_score, np.float32),
            "chosen_score": np.asarray(aux.trace.chosen_score, np.float32),
            "runner_up_gap": np.asarray(aux.trace.runner_up_gap, np.float32),
            "pbest_max": np.asarray(aux.trace.pbest_max, np.float32),
            "pbest_entropy": np.asarray(aux.trace.pbest_entropy, np.float32),
            "surrogate_fallback": np.asarray(aux.trace.surrogate_fallback,
                                             bool),
            "root_key": np.asarray(aux.root_key, np.uint32).reshape(-1, 2),
            "init_key": np.asarray(aux.init_key, np.uint32).reshape(-1, 2),
            "prior_key": np.asarray(aux.prior_key, np.uint32).reshape(-1, 2),
        }
        if crowd is not None:
            arrays["oracle_label"] = np.asarray(crowd.applied_label,
                                                np.int32)
            arrays["label_weight"] = np.asarray(crowd.label_weight,
                                                np.float32)
        # batched acquisition: (S, T, q) decision arrays carry their q in
        # meta so readers never infer it from ranks alone
        ci_shape = arrays["chosen_idx"].shape
        seeds, rounds = ci_shape[0], ci_shape[1]
        acq_batch = int(ci_shape[2]) if arrays["chosen_idx"].ndim == 3 else 1
        meta = {
            "schema_version": RECORD_SCHEMA_VERSION,
            "fingerprint": fingerprint,
            "run": run,
            "trace_k": int(arrays["topk_idx"].shape[-1]),
            "seeds": int(seeds),
            "rounds": int(rounds),
            "acq_batch": acq_batch,
        }
        if extra_meta:
            meta.update(extra_meta)
        return cls(meta=meta, arrays=arrays)

    # -- persistence -------------------------------------------------------
    def save(self, out_dir: str, registry=None) -> dict:
        """Write ``record.json`` + ``rounds.npz`` under ``out_dir``; returns
        {artifact: path} and feeds the recorder counters."""
        t0 = time.perf_counter()
        os.makedirs(out_dir, exist_ok=True)
        paths = {"record": os.path.join(out_dir, "record.json"),
                 "rounds": os.path.join(out_dir, "rounds.npz")}
        # npz first: a crash between the two writes must not leave a
        # record.json pointing at a missing arrays file
        with open(paths["rounds"], "wb") as f:
            np.savez(f, **self.arrays)
        with open(paths["record"], "w") as f:
            json.dump(self.meta, f, indent=2, default=str)
        reg = _counters(registry)
        reg.counter("records_written_total",
                    "Flight-recorder run records written").inc()
        reg.counter("record_rounds_total",
                    "Labeling rounds captured by the flight recorder").inc(
                        float(self.meta["seeds"] * self.meta["rounds"]))
        reg.gauge("recorder_last_write_seconds",
                  "Host seconds to serialize the last run record").set(
                      time.perf_counter() - t0)
        return paths

    @classmethod
    def load(cls, in_dir: str) -> "RunRecord":
        with open(os.path.join(in_dir, "record.json")) as f:
            meta = json.load(f)
        v = meta.get("schema_version")
        if v not in SUPPORTED_RECORD_VERSIONS:
            raise ValueError(
                f"record at {in_dir!r} has schema_version={v!r}; this build "
                f"reads v{SUPPORTED_RECORD_VERSIONS} — re-record or use a "
                "matching checkout")
        with np.load(os.path.join(in_dir, "rounds.npz")) as z:
            arrays = {k: z[k] for k in z.files}
        return cls(meta=meta, arrays=arrays)

    # -- convenience -------------------------------------------------------
    @property
    def seeds(self) -> int:
        return int(self.meta["seeds"])

    @property
    def rounds(self) -> int:
        return int(self.meta["rounds"])

    @property
    def acq_batch(self) -> int:
        """Labels per round (1 for v1 records, which predate batching)."""
        return int(self.meta.get("acq_batch", 1))

    def seed_arrays(self, s: int) -> dict:
        """The per-round arrays of one seed (no leading axis)."""
        return {k: v[s] for k, v in self.arrays.items()}


def is_record_dir(path: str) -> bool:
    return (os.path.isfile(os.path.join(path, "record.json"))
            and os.path.isfile(os.path.join(path, "rounds.npz")))


_STREAM_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


def stream_dir(root: str, *parts: str) -> str:
    """``<root>/<part>/...`` with filesystem-hostile characters squashed
    (task names like ``glue/cola`` must not create surprise nesting)."""
    safe = [_STREAM_SAFE.sub("-", p) for p in parts if p]
    return os.path.join(root, *safe)


def _truncate_torn_tail(path: str) -> None:
    """Drop a torn final line (no trailing newline) from a JSONL stream —
    the leftover of a crash mid-write. Keeps everything through the last
    newline; a file that is ONE torn line truncates to empty."""
    with open(path, "rb+") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            return
        back = min(size, 1 << 20)
        f.seek(size - back)
        tail = f.read(back)
        if tail.endswith(b"\n"):
            return
        cut = tail.rfind(b"\n")
        f.truncate(size - back + cut + 1 if cut >= 0 else 0)


def _count_stream_rows(path: str) -> tuple:
    """``(n_data_rows, resumable)`` for a session stream file. Not
    resumable when a ``session_close`` marker is present (the stream
    ended here — anything after it is a new incarnation, not a
    continuation) or a line fails to parse."""
    n = 0
    with open(path) as f:
        for line in f:
            try:
                kind = json.loads(line).get("kind")
            except ValueError:
                return n, False
            if kind == "session_close":
                return n, False
            if not kind:
                # only DATA rows count toward the resume prefix: marker
                # rows (session_meta, v4's answer_park) are not part of
                # the decision-row sequence import_history aligns on
                n += 1
    return n, True


class SessionRecorder:
    """Per-session decision streams for the serving layer.

    An interactive session has no known end, so its record is a *stream*:
    one in-memory history per live session (the ``GET /session/{id}/trace``
    payload) plus, with an ``out_dir``, an append-only JSONL file per
    session (one meta line, then one versioned row per dispatch) that
    survives a crash mid-session — every ``append`` is flushed.

    Thread-safe: the batcher thread appends, HTTP worker threads read.

    Failure semantics (the disk-full recovery path): a stream write that
    raises (``OSError`` — full disk, dead mount, or the injected
    ``record_eio`` fault) DEGRADES that session's stream to memory-only
    instead of failing the request: the file handle is dropped, the
    session keeps serving, ``degraded_streams`` counts the evidence (and
    rides the ``serve_record_write_errors_total`` registry counter +
    ``/healthz`` degraded status). A clean close writes a
    ``session_close`` marker row so crash restore can tell a finished
    session from one that was live at process death.
    """

    def __init__(self, out_dir: Optional[str] = None, registry=None,
                 faults=None):
        self.out_dir = out_dir
        self._lock = threading.Lock()
        self._history: dict[str, list] = {}
        self._files: dict[str, object] = {}
        self._registry = registry
        self.faults = faults        # optional FaultInjector (record_eio)
        self._task_of: dict[str, str] = {}  # sid -> task (fault filter)
        self.rows_written = 0
        self.degraded_streams = 0   # streams downgraded to memory-only
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)

    def _counter(self):
        return _counters(self._registry).counter(
            "serve_record_rows_total",
            "Per-round decision rows streamed by the serving recorder")

    def _write(self, sid: str, f, line: str) -> None:
        """One flushed stream write; degrades the stream on failure.
        Caller holds ``_lock`` and has already committed the row to the
        in-memory history — a full disk must not lose the session."""
        try:
            if self.faults is not None:
                self.faults.fire("record_write",
                                 task=self._task_of.get(sid))
            f.write(line)
            f.flush()  # crash-mid-session keeps every completed row
        except OSError as e:
            self._files.pop(sid, None)
            self.degraded_streams += 1
            try:
                f.close()
            except OSError:
                pass
            _counters(self._registry).counter(
                "serve_record_write_errors_total",
                "Recorder stream writes that failed; the stream degraded "
                "to memory-only").inc()
            import sys

            print(f"recorder: stream for session {sid} degraded to "
                  f"memory-only ({e})", file=sys.stderr)

    def open(self, sid: str, meta: Optional[dict] = None) -> None:
        with self._lock:
            self._history[sid] = []
            if meta and meta.get("task"):
                self._task_of[sid] = meta["task"]
            if self.out_dir:
                f = open(os.path.join(self.out_dir,
                                      f"session_{sid}.jsonl"), "a")
                self._files[sid] = f
                header = {"v": SESSION_SCHEMA_VERSION, "kind": "session_meta",
                          "session": sid}
                header.update(meta or {})
                self._write(sid, f, json.dumps(header, default=str) + "\n")

    def import_history(self, sid: str, meta: Optional[dict] = None,
                       rows=()) -> None:
        """Seed a session's history from an imported/restored stream.

        The portable session log moves WITH the session: on a fresh
        record dir the full history (meta + rows) is written so the new
        server's stream is self-contained; when the stream file already
        exists here AND is a live prefix of the imported rows (crash
        restore against the same dir), it is resumed by appending only
        the missing suffix — a file that is closed (the session migrated
        away from this dir and is now coming back), unreadable, or ahead
        of the payload is rewritten whole, since appending after a close
        marker or a row gap would leave a stream a later crash restore
        replays into a false divergence. A resumed file may end in a
        TORN line (the crash the restore is recovering from happened
        mid-write); that tail is truncated before appending —
        concatenating a new row onto the fragment would corrupt a
        mid-file line and make the stream unreadable."""
        rows = [dict(r) for r in rows]
        path = (os.path.join(self.out_dir, f"session_{sid}.jsonl")
                if self.out_dir else None)
        resume = path is not None and os.path.exists(path)
        n_existing = 0
        if resume:
            _truncate_torn_tail(path)
            n_existing, resumable = _count_stream_rows(path)
            if not resumable or n_existing > len(rows):
                resume, n_existing = False, 0
        with self._lock:
            self._history[sid] = rows
            if meta and meta.get("task"):
                self._task_of[sid] = meta["task"]
            if path is None:
                return
            f = open(path, "a" if resume else "w")
            self._files[sid] = f
            lines = []
            if not resume:
                header = {"v": SESSION_SCHEMA_VERSION,
                          "kind": "session_meta", "session": sid}
                header.update(meta or {})
                lines.append(json.dumps(header, default=str))
            lines += [json.dumps(dict(r, v=SESSION_SCHEMA_VERSION),
                                 default=str) for r in rows[n_existing:]]
            if lines:
                self._write(sid, f, "\n".join(lines) + "\n")

    def append(self, sid: str, row: dict) -> None:
        with self._lock:
            hist = self._history.get(sid)
            if hist is None:
                return  # session closed (or never opened) while queued
            row = dict(row, v=SESSION_SCHEMA_VERSION)
            hist.append(row)
            self.rows_written += 1
            f = self._files.get(sid)
            if f is not None:
                self._write(sid, f, json.dumps(row, default=str) + "\n")
        self._counter().inc()

    def history(self, sid: str) -> Optional[list]:
        with self._lock:
            hist = self._history.get(sid)
            return list(hist) if hist is not None else None

    def park(self, sid: str) -> None:
        """Release a live stream's host resources WITHOUT ending it — the
        warm-tier demotion hook (serve/tiering.py): the fd closes (100k
        parked sessions must not hold 100k file handles) and the
        in-memory history drops (the demotion payload carries the rows),
        but NO close marker is written — the stream is still a live
        session's record, and crash restore must rebuild it. Wake resumes
        the file through :meth:`import_history`'s append path."""
        with self._lock:
            self._history.pop(sid, None)
            self._task_of.pop(sid, None)
            f = self._files.pop(sid, None)
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def seal(self, sid: str) -> None:
        """End a PARKED session's stream: append the ``session_close``
        marker to its file (hibernate/discard of a non-resident session —
        from there the hibernate payload, or nothing, is the authority
        and crash restore must skip the stream). A still-live stream is
        closed normally instead."""
        with self._lock:
            live = sid in self._files or sid in self._history
        if live:
            self.close(sid)
            return
        if not self.out_dir:
            return
        path = os.path.join(self.out_dir, f"session_{sid}.jsonl")
        if not os.path.exists(path):
            return
        try:
            with open(path, "a") as f:
                f.write(json.dumps(
                    {"v": SESSION_SCHEMA_VERSION, "kind": "session_close",
                     "session": sid}) + "\n")
        except OSError:
            pass

    def close(self, sid: str) -> None:
        with self._lock:
            self._history.pop(sid, None)
            self._task_of.pop(sid, None)
            f = self._files.pop(sid, None)
            if f is not None:
                # the clean-shutdown marker crash restore keys on: a
                # stream WITHOUT it was live when the process died
                try:
                    f.write(json.dumps(
                        {"v": SESSION_SCHEMA_VERSION,
                         "kind": "session_close", "session": sid}) + "\n")
                    f.flush()
                except OSError:
                    pass
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def close_all(self) -> None:
        with self._lock:
            files = list(self._files.items())
            self._files.clear()
            self._history.clear()
            self._task_of.clear()
        for sid, f in files:
            try:
                f.write(json.dumps(
                    {"v": SESSION_SCHEMA_VERSION, "kind": "session_close",
                     "session": sid}) + "\n")
                f.close()
            except OSError:
                pass
