"""The functional selector protocol.

The reference defines a 3-method OO protocol with mutable state (reference
``coda/base.py:1-16``: ``get_next_item_to_label`` / ``add_label`` /
``get_best_model_prediction``). For TPU execution the same capability is
recast as four *pure functions over a fixed-shape state pytree*, so a whole
labeling experiment compiles into one ``lax.scan`` and seeds batch under
``vmap``:

    init(key)                          -> state
    select(state, key)                 -> SelectResult(idx, prob, stochastic)
    update(state, idx, true_class, p)  -> state
    best(state, key)                   -> (best model index, stochastic)

``stochastic`` reports whether randomness affected that call (tie-breaks,
random sampling) — the reference's per-selector ``stochastic`` flag that
lets the driver skip redundant seeds of deterministic methods.

A factory ``make_<method>(preds, hp...) -> Selector`` closes each function
over the prediction tensor and any precomputed statics (hard argmax preds,
disagreement masks, per-point losses), which keeps ``state`` small — that is
what gets carried through scan and checkpointed.

Host-driven consumers (the Gradio demo, step-by-step debugging) use the
``InteractiveSelector`` wrapper, which exposes the reference's original
mutable 3-method API on top of the pure functions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class SelectResult(NamedTuple):
    idx: jnp.ndarray        # scalar int32 — chosen data point
    prob: jnp.ndarray       # scalar float32 — selection probability / q-value
    stochastic: jnp.ndarray  # scalar bool — did randomness affect this choice?
    # (N,) float32 acquisition-utility vector, or None. Convention: higher =
    # more preferred (argmin acquisitions negate), non-candidates masked to
    # -inf. Selectors already materialize this vector to take their argmax,
    # so returning it is free — XLA dead-code-eliminates it everywhere except
    # the flight-recorder step, which reads its top-k per round
    # (engine/loop.py make_step_fn(trace_k=...)).
    scores: Any = None


@dataclass(frozen=True)
class Selector:
    """A bundle of pure functions implementing one selection method."""

    name: str
    init: Callable[[jax.Array], Any]
    select: Callable[[Any, jax.Array], SelectResult]
    update: Callable[[Any, jnp.ndarray, jnp.ndarray, jnp.ndarray], Any]
    best: Callable[[Any, jax.Array], jnp.ndarray]
    # -- batched acquisition (the --acq-batch q protocol) ------------------
    # select_q(state, key, q): pick q DISTINCT points in one scoring pass,
    # returning a SelectResult whose idx/prob carry a leading (q,) axis
    # (q is a static Python int). None = the method has no native batched
    # acquisition; `selectors/batch.py` then derives a generic greedy
    # top-q from the (N,) score vector `select` already emits.
    # update_q(state, idxs, true_classes, probs) with (q,) arrays: apply
    # all q oracle answers as ONE fused update (multi-row posterior
    # scatter + a single batched refresh) instead of q sequential steps.
    # None = batch.py falls back to a lax.scan of `update` (correct, not
    # fused). q == 1 never routes through either: the legacy single-label
    # program runs unchanged (bitwise pin).
    select_q: Optional[Callable[[Any, jax.Array, int], SelectResult]] = None
    update_q: Optional[Callable[[Any, jnp.ndarray, jnp.ndarray,
                                 jnp.ndarray], Any]] = None
    # -- reliability-weighted updates (the crowd-oracle protocol) ----------
    # update_w(state, idx, true_class, prob, w): the single-label update
    # with a traced scalar weight w scaling the posterior increment
    # (effective strength = learning_rate * w). Contract: w=1 is bitwise
    # the exact `update`; w=0 is a structural no-op on the posterior.
    # update_qw(state, idxs, true_classes, probs, ws) with (q,) arrays is
    # the fused q-wide analog. None = the method has no weighted path;
    # `selectors/batch.py` derives update_qw from update_w when present,
    # and the crowd loop refuses methods without update_w (weighting is
    # meaningless for loss-table methods that never carry a posterior).
    update_w: Optional[Callable[[Any, jnp.ndarray, jnp.ndarray,
                                 jnp.ndarray, jnp.ndarray], Any]] = None
    update_qw: Optional[Callable[[Any, jnp.ndarray, jnp.ndarray,
                                  jnp.ndarray, jnp.ndarray], Any]] = None
    # True when the method is stochastic by construction (e.g. IID sampling);
    # deterministic methods let the driver skip redundant seeds, mirroring the
    # reference's `stochastic` early-stop (reference main.py:128-130).
    always_stochastic: bool = False
    hyperparams: dict = field(default_factory=dict)
    # construction defaults of the hyperparams (e.g. Hyperparams()._asdict());
    # lets checkpoints written before a hyperparam existed keep resuming —
    # but only when the new field is at its default value
    hyperparam_defaults: dict = field(default_factory=dict)
    # extra method-specific pure functions (e.g. CODA's get_pbest) for demos
    # and diagnostics; not part of the scan loop
    extras: dict = field(default_factory=dict)


class InteractiveSelector:
    """Mutable, host-driven wrapper with the reference's 3-method API."""

    def __init__(self, selector: Selector, seed: int = 0):
        self.selector = selector
        self._key = jax.random.PRNGKey(seed)
        self.state = jax.jit(selector.init)(self._next_key())
        self._select = jax.jit(selector.select)
        self._update = jax.jit(selector.update)
        self._best = jax.jit(selector.best)
        self.stochastic = selector.always_stochastic
        self.labeled_idxs: list[int] = []
        self.labels: list[int] = []
        self.q_vals: list[float] = []

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_next_item_to_label(self):
        res = self._select(self.state, self._next_key())
        if bool(res.stochastic):
            self.stochastic = True
        return int(res.idx), float(res.prob)

    def add_label(self, idx: int, true_class: int, selection_prob: float = 0.0):
        self.state = self._update(
            self.state,
            jnp.asarray(idx, jnp.int32),
            jnp.asarray(true_class, jnp.int32),
            jnp.asarray(selection_prob, jnp.float32),
        )
        self.labeled_idxs.append(int(idx))
        self.labels.append(int(true_class))
        self.q_vals.append(float(selection_prob))

    def get_best_model_prediction(self) -> int:
        idx, stochastic = self._best(self.state, self._next_key())
        if bool(stochastic):
            self.stochastic = True
        return int(idx)
