"""ActiveTesting (Kossen et al. 2021) with LURE risk estimation.

Capability parity with reference ``coda/baselines/activetesting.py``:
  * surrogate = mean ensemble of all candidates; acquisition score of a point
    is the summed expected loss ``Σ_h (1 - π_ens(ŷ_h))``, sampled
    proportionally over unlabeled points;
  * best model = argmin of the LURE importance-weighted risk
    (Farquhar et al. 2021): ``v_m = 1 + (N-M)/(N-m) * (1/((N-m+1) q_m) - 1)``.

TPU shape: the acquisition base scores are a static ``(N,)`` vector (the
surrogate never changes), so each round only renormalizes over the unlabeled
mask and draws one categorical sample. The per-round loss vectors and
selection probabilities live in fixed ``(H, T)`` / ``(T,)`` ring buffers
(T = label budget), making the LURE readout a masked reduction.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.losses import accuracy_loss
from coda_tpu.ops.masked import masked_argmin_tiebreak, masked_categorical
from coda_tpu.selectors.protocol import Selector, SelectResult


class LUREState(NamedTuple):
    unlabeled: jnp.ndarray   # (N,) bool
    losses: jnp.ndarray      # (H, T) per-step losses of each model at picks
    qs: jnp.ndarray          # (T,) selection probabilities
    n_labeled: jnp.ndarray   # scalar int32 (M)


def surrogate_expected_losses(preds: jnp.ndarray) -> jnp.ndarray:
    """(H, N): surrogate prob that model h is wrong on point n."""
    pi_y = preds.mean(axis=0)                       # (N, C) ensemble surrogate
    pred_cls = preds.argmax(axis=2)                 # (H, N)
    # size-1 leading dim broadcasts — no (H, N, C) copy of the surrogate
    y_star = jnp.take_along_axis(
        pi_y[None, :, :], pred_cls[..., None], axis=2
    )[..., 0]
    return 1.0 - y_star


def lure_risks_and_vars(
    losses: jnp.ndarray,   # (H, T)
    qs: jnp.ndarray,       # (T,)
    M: jnp.ndarray,        # scalar int
    N: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """LURE risk estimates and estimator variances, both (H,).

    Masked over the first M buffer slots. Matches the reference's
    ``get_lure_risks_and_vars`` (reference
    ``coda/baselines/activetesting.py:69-90``): estimate = mean of the
    v-weighted losses, variance = unbiased sample variance of the weighted
    losses divided by M. Divergence: at M <= 1 the reference's unbiased
    variance is NaN (0/0); we return 0 there (masked reductions can't emit
    the reference's accidental NaN, and callers only consume the variance
    once labels exist).
    """
    T = qs.shape[0]
    m_idx = jnp.arange(1, T + 1, dtype=jnp.float32)     # 1-indexed m
    Mf = M.astype(jnp.float32)
    valid = (m_idx <= Mf)
    v = 1.0 + ((N - Mf) / (N - m_idx)) * (
        1.0 / ((N - m_idx + 1.0) * jnp.clip(qs, 1e-30, None)) - 1.0
    )
    v = jnp.where(valid, v, 0.0)
    weighted = v[None, :] * losses                      # (H, T)
    mean = weighted.sum(axis=1) / jnp.clip(Mf, 1.0, None)
    sq_dev = jnp.where(valid[None, :],
                       (weighted - mean[:, None]) ** 2, 0.0)
    sample_var = sq_dev.sum(axis=1) / jnp.clip(Mf - 1.0, 1.0, None)
    return mean, sample_var / jnp.clip(Mf, 1.0, None)


def lure_risks(
    losses: jnp.ndarray,   # (H, T)
    qs: jnp.ndarray,       # (T,)
    M: jnp.ndarray,        # scalar int
    N: int,
) -> jnp.ndarray:
    """LURE risk estimates (H,); masked over the first M buffer slots."""
    return lure_risks_and_vars(losses, qs, M, N)[0]


def make_activetesting(
    preds: jnp.ndarray,
    loss_fn: Callable = accuracy_loss,
    budget: int = 128,
    name: str = "activetesting",
    acquisition_scores: jnp.ndarray | None = None,
) -> Selector:
    H, N, C = preds.shape
    if acquisition_scores is None:
        acquisition_scores = surrogate_expected_losses(preds).sum(axis=0)  # (N,)

    def init(key):
        del key
        return LUREState(
            unlabeled=jnp.ones((N,), dtype=bool),
            losses=jnp.zeros((H, budget), dtype=jnp.float32),
            qs=jnp.zeros((budget,), dtype=jnp.float32),
            n_labeled=jnp.asarray(0, jnp.int32),
        )

    def select(state, key) -> SelectResult:
        idx, prob = masked_categorical(key, acquisition_scores, state.unlabeled)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=prob,
            stochastic=jnp.asarray(True),
            # proportional sampling: the utility is the (unnormalized)
            # acquisition weight — the quantity whose ordering the flight
            # recorder's top-k should capture
            scores=jnp.where(state.unlabeled, acquisition_scores, -jnp.inf),
        )

    def update(state, idx, true_class, prob):
        loss_vec = loss_fn(preds[:, idx, :], jnp.full((H,), true_class))
        m = state.n_labeled
        return LUREState(
            unlabeled=state.unlabeled.at[idx].set(False),
            losses=state.losses.at[:, m].set(loss_vec),
            qs=state.qs.at[m].set(prob),
            n_labeled=m + 1,
        )

    def select_q(state, key, q: int) -> SelectResult:
        """q sequential proportional draws WITHOUT replacement from the
        static acquisition weights — each draw's recorded probability is
        conditional on the picks before it (exactly the q_m the LURE
        weights need: the batch is q single-draw rounds whose oracle
        answers arrive together)."""
        keys = jax.random.split(key, q)

        def draw(carry, kt):
            mask = carry
            idx_t, prob_t = masked_categorical(kt, acquisition_scores, mask)
            return mask.at[idx_t].set(False), (idx_t.astype(jnp.int32),
                                               prob_t)

        _, (idxs, probs) = lax.scan(draw, state.unlabeled, keys)
        return SelectResult(
            idx=idxs,
            prob=probs.astype(jnp.float32),
            stochastic=jnp.asarray(True),
            scores=jnp.where(state.unlabeled, acquisition_scores,
                             -jnp.inf),
        )

    def update_q(state, idxs, true_classes, probs):
        """One fused update: the q loss vectors are computed in a single
        (H, q) batch, then land as q unrolled column writes at slots
        ``m..m+q-1`` — scalar-index ``.at`` scatters, whose out-of-bounds
        writes DROP exactly like the q=1 path's (a ``dynamic_update_slice``
        block write would instead CLAMP at the ring edge and overwrite
        committed history when a serving session runs past the LURE
        budget)."""
        q = idxs.shape[0]
        loss_blk = loss_fn(preds[:, idxs, :],
                           jnp.broadcast_to(true_classes[None, :],
                                            (H, q)))          # (H, q)
        m = state.n_labeled
        losses, qs = state.losses, state.qs
        for j in range(q):
            losses = losses.at[:, m + j].set(
                loss_blk[:, j].astype(jnp.float32))
            qs = qs.at[m + j].set(probs[j].astype(jnp.float32))
        return LUREState(
            unlabeled=state.unlabeled.at[idxs].set(False),
            losses=losses,
            qs=qs,
            n_labeled=m + q,
        )

    def best(state, key):
        risk = lure_risks(state.losses, state.qs, state.n_labeled, N)
        k_tie, k_rand = jax.random.split(key)
        idx, n_ties = masked_argmin_tiebreak(k_tie, risk,
                                             jnp.ones((H,), dtype=bool))
        # no labels yet -> uniformly random model (reference behavior)
        rand_idx = jax.random.randint(k_rand, (), 0, H)
        chose_random = (state.n_labeled == 0) | (n_ties > 1)
        return (jnp.where(state.n_labeled > 0, idx, rand_idx).astype(jnp.int32),
                chose_random)

    return Selector(
        name=name, init=init, select=select, update=update, best=best,
        select_q=select_q, update_q=update_q,
        always_stochastic=True,
        hyperparams={"budget": budget},
        extras={
            "lure_risks": lambda s: lure_risks(s.losses, s.qs, s.n_labeled, N),
            "lure_risks_and_vars": lambda s: lure_risks_and_vars(
                s.losses, s.qs, s.n_labeled, N),
        },
    )
