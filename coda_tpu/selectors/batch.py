"""Batched acquisition: q oracle labels per round, one fused update.

The paper's protocol acquires exactly ONE label per round, but production
oracles (crowd annotators, labeling services) answer in parallel. This
module is the generic half of the ``--acq-batch q`` machinery: given any
:class:`~coda_tpu.selectors.protocol.Selector`, it resolves the pair of
q-wide pure functions the engine's scan step (and the serving slab step)
drive instead of ``select``/``update``:

  * ``select_q(state, key) -> SelectResult`` with a leading ``(q,)`` axis
    on ``idx``/``prob`` — q DISTINCT points from ONE scoring pass. A
    selector that declares its own ``select_q`` (CODA's greedy EIG with
    the information-overlap penalty, ModelPicker's argmin top-q,
    ActiveTesting's sequential proportional draws) is used verbatim;
    otherwise :func:`generic_select_q` derives a greedy top-q from the
    ``(N,)`` score vector ``select`` already emits (pick 1 is the
    method's OWN choice — same randomness class as q=1 — and picks 2..q
    re-rank the same scores with picked points masked out, never
    re-scoring).
  * ``update_q(state, idxs, true_classes, probs) -> state`` — all q
    oracle answers applied at once. A selector-provided ``update_q`` is
    the FUSED path (multi-row posterior scatter + one batched refresh);
    the fallback is a ``lax.scan`` of the single-label ``update``
    (sequentially correct, not fused — e.g. the pallas scoring backends,
    whose in-kernel refresh is single-row).

``q == 1`` never routes through this module: the engine keeps the legacy
single-label program bitwise unchanged (the tier-1 pin). The scorer seam
stays pluggable — select_q consumes whatever score vector the selector's
scoring rung produced (exact quadrature, the Laplace-bridge rung, or a
future learned surrogate à la LINNA arXiv 2203.05583), so new rungs
compose with batching for free.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.ops.masked import masked_argmax_tiebreak
from coda_tpu.selectors.protocol import Selector, SelectResult

# the tie tolerance of the generic greedy re-rank picks (2..q) — the same
# reference rule CODA's argmax uses (coda.py: isclose rtol=atol=1e-8)
_TIE_RTOL = 1e-8
_TIE_ATOL = 1e-8


def generic_select_q(selector: Selector, q: int) -> Callable:
    """Greedy top-q over the selector's own score vector, one scoring pass.

    Pick 1 is the method's own ``select`` (same key, same tie-break /
    sampling semantics as the q=1 round). Picks 2..q are masked argmaxes
    over the SAME scores with already-picked points removed — a cached
    re-rank, not q scoring passes. When the finite-score candidate set
    runs dry mid-batch (e.g. a disagreement prefilter smaller than q),
    later picks fall back to the ``unlabeled`` mask every selector state
    carries (protocol convention), scored at -inf — distinctness is the
    invariant, not score order.
    """
    if q < 2:
        raise ValueError("generic_select_q is the q >= 2 path")

    def select_q(state, key) -> SelectResult:
        res = selector.select(state, key)
        scores = res.scores
        if scores is None:
            raise ValueError(
                f"selector {selector.name!r} emits no score vector; "
                "--acq-batch > 1 needs one (SelectResult.scores) for the "
                "greedy top-q re-rank")
        N = scores.shape[0]
        picked0 = jnp.zeros((N,), bool).at[res.idx].set(True)
        keys = jax.random.split(jax.random.fold_in(key, 0x6ba7c9), q - 1)

        def pick(carry, kt):
            picked, any_tie = carry
            avail = jnp.isfinite(scores) & ~picked
            fallback = state.unlabeled & ~picked
            cand = jnp.where(avail.any(), avail, fallback)
            idx_t, n_ties = masked_argmax_tiebreak(
                kt, jnp.where(avail, scores, -jnp.inf), cand,
                rtol=_TIE_RTOL, atol=_TIE_ATOL)
            return ((picked.at[idx_t].set(True), any_tie | (n_ties > 1)),
                    (idx_t.astype(jnp.int32), scores[idx_t]))

        (_, any_tie), (idxs, probs) = lax.scan(
            pick, (picked0, jnp.asarray(False)), keys)
        return SelectResult(
            idx=jnp.concatenate([res.idx.astype(jnp.int32)[None], idxs]),
            prob=jnp.concatenate([res.prob.astype(jnp.float32)[None],
                                  probs.astype(jnp.float32)]),
            stochastic=res.stochastic | any_tie,
            scores=scores,
        )

    return select_q


def generic_update_q(selector: Selector) -> Callable:
    """Sequential fallback: a ``lax.scan`` of the single-label ``update``
    — correct for any selector, but q refresh passes instead of one
    (selectors on the hot path provide a fused ``update_q`` instead)."""

    def update_q(state, idxs, true_classes, probs):
        def body(st, xs):
            i, t, p = xs
            return selector.update(st, i, t, p), None

        st, _ = lax.scan(body, state, (idxs, true_classes, probs))
        return st

    return update_q


def generic_update_qw(selector: Selector) -> Callable:
    """Sequential fallback for the WEIGHTED q-wide update: a ``lax.scan``
    of the single-label ``update_w`` (same shape as
    :func:`generic_update_q`, one extra scanned leaf for the per-answer
    weights)."""
    if selector.update_w is None:
        raise ValueError(
            f"selector {selector.name!r} has no weighted update "
            "(update_w); reliability-weighted crowd rounds need one")

    def update_qw(state, idxs, true_classes, probs, ws):
        def body(st, xs):
            i, t, p, w = xs
            return selector.update_w(st, i, t, p, w), None

        st, _ = lax.scan(body, state, (idxs, true_classes, probs, ws))
        return st

    return update_qw


def resolve_batch_wfns(selector: Selector, q: int):
    """The weighted analog of :func:`resolve_batch_fns`: the concrete
    ``(select_q(state, key), update_qw(state, idxs, tcs, probs, ws))``
    pair for a static q >= 2 — the selector's fused ``update_qw`` when
    declared, the scanned ``update_w`` fallback otherwise."""
    sel_q, _ = resolve_batch_fns(selector, q)
    upd_qw = (selector.update_qw if selector.update_qw is not None
              else generic_update_qw(selector))
    return sel_q, upd_qw


def resolve_batch_fns(selector: Selector, q: int):
    """The concrete ``(select_q(state, key), update_q(state, idxs, tcs,
    probs))`` pair for a static batch width ``q >= 2`` — selector-native
    implementations when declared, generic derivations otherwise."""
    if q < 2:
        raise ValueError(f"acq_batch={q}: the batched pair is the q >= 2 "
                         "path (q == 1 runs the legacy program)")
    if selector.select_q is not None:
        def sel_q(state, key, _f=selector.select_q):
            return _f(state, key, q)
    else:
        sel_q = generic_select_q(selector, q)
    upd_q = (selector.update_q if selector.update_q is not None
             else generic_update_q(selector))
    return sel_q, upd_q


def make_batched_selector(selector: Selector, q: int) -> Selector:
    """A :class:`Selector` whose ``select``/``update`` ARE the q-wide pair
    — the adapter the serving slab step drives, so a ``(task, spec,
    acq_batch=q)`` bucket's one compiled program batches labels without
    the slab machinery knowing about q at all (shapes just carry a
    trailing ``(q,)``)."""
    sel_q, upd_q = resolve_batch_fns(selector, q)
    return dataclasses.replace(
        selector, select=sel_q, update=upd_q,
        select_q=None, update_q=None,
        hyperparams=dict(selector.hyperparams, acq_batch=q))
