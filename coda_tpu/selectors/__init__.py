from coda_tpu.selectors.protocol import Selector, SelectResult
from coda_tpu.selectors.coda import make_coda, CODAHyperparams
from coda_tpu.selectors.iid import make_iid
from coda_tpu.selectors.uncertainty import make_uncertainty
from coda_tpu.selectors.activetesting import make_activetesting
from coda_tpu.selectors.vma import make_vma
from coda_tpu.selectors.modelpicker import make_modelpicker, TASK_EPS

SELECTOR_FACTORIES = {
    "iid": make_iid,
    "uncertainty": make_uncertainty,
    "coda": make_coda,
    "activetesting": make_activetesting,
    "vma": make_vma,
    "model_picker": make_modelpicker,
}

__all__ = [
    "Selector",
    "SelectResult",
    "make_coda",
    "CODAHyperparams",
    "make_iid",
    "make_uncertainty",
    "make_activetesting",
    "make_vma",
    "make_modelpicker",
    "TASK_EPS",
    "SELECTOR_FACTORIES",
]
