"""IID random-sampling baseline.

Capability parity with reference ``coda/baselines/iid.py``: uniform random
acquisition over unlabeled points; best model = argmin of empirical mean loss
on the labeled set, ties broken uniformly at random.

TPU shape: labeled set is a boolean mask; the risk is maintained
*incrementally* — ``update`` adds the ``(H,)`` loss vector of the one new
point to a running total, so the per-round cost is O(H) instead of
re-evaluating ``loss_fn`` over the full ``(H, N, C)`` tensor inside the
scan (which at DomainNet scale made this trivial baseline as slow as
CODA's EIG). State stays O(N + H) and every function is jit/scan-safe.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from coda_tpu.losses import accuracy_loss
from coda_tpu.ops.masked import masked_argmin_tiebreak
from coda_tpu.selectors.protocol import Selector, SelectResult


class RiskState(NamedTuple):
    """Shared state for risk-readout selectors (IID, Uncertainty)."""

    unlabeled: jnp.ndarray    # (N,) bool
    loss_total: jnp.ndarray   # (H,) summed loss of each model on labeled pts
    n_labeled: jnp.ndarray    # scalar int32


def make_risk_readout(preds: jnp.ndarray, loss_fn: Callable):
    """Returns ``(init_state, risk, best, update)`` pure fns over RiskState.

    Shared by IID and Uncertainty (they differ only in acquisition)."""
    H, N, C = preds.shape

    def init_state() -> RiskState:
        return RiskState(
            unlabeled=jnp.ones((N,), dtype=bool),
            loss_total=jnp.zeros((H,), jnp.float32),
            n_labeled=jnp.asarray(0, jnp.int32),
        )

    def risk(state) -> jnp.ndarray:
        n = jnp.clip(state.n_labeled.astype(jnp.float32), 1.0, None)
        return state.loss_total / n

    def best(state, key):
        r = risk(state)
        idx, n_ties = masked_argmin_tiebreak(key, r, jnp.ones((H,), dtype=bool))
        # risk ties (common early on with few labels) are broken randomly and
        # make the run stochastic (reference iid.py get_best_model_prediction)
        return idx.astype(jnp.int32), n_ties > 1

    def update(state, idx, true_class, prob) -> RiskState:
        del prob
        loss_vec = loss_fn(preds[:, idx, :], jnp.full((H,), true_class))
        return RiskState(
            unlabeled=state.unlabeled.at[idx].set(False),
            loss_total=state.loss_total + loss_vec.astype(jnp.float32),
            n_labeled=state.n_labeled + 1,
        )

    return init_state, risk, best, update


def make_iid(
    preds: jnp.ndarray,
    loss_fn: Callable = accuracy_loss,
    name: str = "iid",
) -> Selector:
    H, N, C = preds.shape
    init_state, risk, best, update = make_risk_readout(preds, loss_fn)

    def init(key):
        del key
        return init_state()

    def select(state, key) -> SelectResult:
        n_u = state.unlabeled.sum()
        logits = jnp.where(state.unlabeled, 0.0, -jnp.inf)
        idx = jax.random.categorical(key, logits)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=1.0 / n_u.astype(jnp.float32),
            stochastic=jnp.asarray(True),
            # uniform acquisition: each candidate's utility is its selection
            # probability (flight-recorder top-k then reads all-equal scores,
            # which the triage classifier treats as a maximal tie)
            scores=jnp.where(state.unlabeled,
                             1.0 / n_u.astype(jnp.float32), -jnp.inf),
        )

    return Selector(
        name=name, init=init, select=select, update=update, best=best,
        always_stochastic=True, extras={"risk": risk},
    )
