"""IID random-sampling baseline.

Capability parity with reference ``coda/baselines/iid.py``: uniform random
acquisition over unlabeled points; best model = argmin of empirical mean loss
on the labeled set, ties broken uniformly at random.

TPU shape: labeled set is a boolean mask + an ``(N,)`` acquired-label array;
the risk readout is a masked mean over a per-point loss table evaluated on
the fly, so state stays O(N) and every function is jit/scan-safe.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from coda_tpu.losses import accuracy_loss
from coda_tpu.ops.masked import masked_argmin_tiebreak
from coda_tpu.selectors.protocol import Selector, SelectResult


class RiskState(NamedTuple):
    """Shared state for risk-readout selectors (IID, Uncertainty)."""

    unlabeled: jnp.ndarray    # (N,) bool
    labels_acq: jnp.ndarray   # (N,) int32; meaningful only where ~unlabeled
    n_labeled: jnp.ndarray    # scalar int32


def make_risk_readout(preds: jnp.ndarray, loss_fn: Callable):
    """Returns (risk, best) pure fns over RiskState-compatible states."""
    H, N, C = preds.shape

    def risk(state) -> jnp.ndarray:
        # (H, N) losses against acquired labels; unlabeled columns masked out
        losses = loss_fn(preds, state.labels_acq[None, :])
        labeled = (~state.unlabeled).astype(losses.dtype)
        total = (losses * labeled[None, :]).sum(axis=1)
        return total / jnp.clip(state.n_labeled.astype(losses.dtype), 1.0, None)

    def best(state, key):
        r = risk(state)
        idx, n_ties = masked_argmin_tiebreak(key, r, jnp.ones((H,), dtype=bool))
        # risk ties (common early on with few labels) are broken randomly and
        # make the run stochastic (reference iid.py get_best_model_prediction)
        return idx.astype(jnp.int32), n_ties > 1

    return risk, best


def make_iid(
    preds: jnp.ndarray,
    loss_fn: Callable = accuracy_loss,
    name: str = "iid",
) -> Selector:
    H, N, C = preds.shape
    risk, best = make_risk_readout(preds, loss_fn)

    def init(key):
        del key
        return RiskState(
            unlabeled=jnp.ones((N,), dtype=bool),
            labels_acq=jnp.zeros((N,), dtype=jnp.int32),
            n_labeled=jnp.asarray(0, jnp.int32),
        )

    def select(state, key) -> SelectResult:
        n_u = state.unlabeled.sum()
        logits = jnp.where(state.unlabeled, 0.0, -jnp.inf)
        idx = jax.random.categorical(key, logits)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=1.0 / n_u.astype(jnp.float32),
            stochastic=jnp.asarray(True),
        )

    def update(state, idx, true_class, prob):
        del prob
        return RiskState(
            unlabeled=state.unlabeled.at[idx].set(False),
            labels_acq=state.labels_acq.at[idx].set(true_class),
            n_labeled=state.n_labeled + 1,
        )

    return Selector(
        name=name, init=init, select=select, update=update, best=best,
        always_stochastic=True, extras={"risk": risk},
    )
