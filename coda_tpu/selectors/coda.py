"""CODA: consensus-driven active model selection, TPU-native.

Capability parity with the reference method (reference ``coda/coda.py:171-346``
and its kernel functions at ``:14-168``), re-architected for XLA:

  * selector state is a fixed-shape pytree (Dirichlet posteriors + masks),
    not Python lists — jit/scan/vmap-able and trivially checkpointable;
  * the EIG acquisition is a vmapped pure function over *all* N points with
    candidate masking at argmax time, chunked only as a memory valve via
    ``lax.map(..., batch_size=...)`` (the reference chunks a Python loop at
    100 items/iter, ``coda/coda.py:261``);
  * the P(best) integral's serial CDF loop is replaced by a parallel
    cumulative trapezoid (see ``coda_tpu/ops/pbest.py``);
  * the consensus prefilter (drop points where every model agrees,
    ``coda/coda.py:215-224``) becomes a static boolean mask; the optional
    ``prefilter_n`` random subsample becomes a top-k over masked uniforms.

Numeric choreography (grid endpoints, eps floors, +-80 clamps, fp32
everywhere, HIGHEST-precision einsums) follows the reference so the EIG
argmax ordering — and therefore the label-selection trace — matches.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.ops.beta import beta_log_pdf, cumtrapz_uniform, dirichlet_to_beta
from coda_tpu.ops.confusion import (
    create_confusion_matrices,
    ensemble_preds,
    initialize_dirichlets,
)
from coda_tpu.ops.masked import entropy2, masked_argmax_tiebreak
from coda_tpu.ops.pbest import _EPS, compute_pbest, pbest_grid, pbest_row_mixture
from coda_tpu.selectors.protocol import Selector, SelectResult

_PRECISION = lax.Precision.HIGHEST
# reference coda/coda.py:307 uses isclose(rtol=1e-8) with torch's default
# atol=1e-8; atol dominates for tiny EIG entropy deltas
_TIE_RTOL = 1e-8
_TIE_ATOL = 1e-8


class CODAHyperparams(NamedTuple):
    prefilter_n: int = 0
    alpha: float = 0.9            # prior_strength = 1 - alpha (coda/coda.py:189)
    learning_rate: float = 0.01   # update_strength
    multiplier: float = 2.0
    disable_diag_prior: bool = False  # ablation 1
    q: str = "eig"                # acquisition: eig | iid | uncertainty (ablation 2)
    eig_chunk: int = 256          # memory valve for the EIG map
    num_points: int = 256         # P(best) integration grid
    eig_mode: str = "factored"    # factored (MXU, default) | direct (reference
    #                               numeric choreography, kept for cross-checks)


class CODAState(NamedTuple):
    dirichlets: jnp.ndarray    # (H, C, C) Dirichlet confusion posteriors
    pi_hat_xi: jnp.ndarray     # (N, C) per-item class posterior
    pi_hat: jnp.ndarray        # (C,) marginal class estimate
    unlabeled: jnp.ndarray     # (N,) bool


def update_pi_hat(
    dirichlets: jnp.ndarray, preds: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dirichlet-adjusted class posterior per item + dataset marginal.

    ``adjusted[h,n,c] = Σ_s dirichlets[h,c,s] * preds[h,n,s]`` summed over
    models (reference ``coda/coda.py:226-233``) — a batched matmul that maps
    straight onto the MXU.
    """
    # contract models inside the einsum: the (H, N, C) adjusted tensor (2 GB
    # at M=1k, N=50k) never materializes — one MXU pass straight to (N, C)
    pi_xi = jnp.einsum("hcs,hns->nc", dirichlets, preds, precision=_PRECISION)
    pi_xi = pi_xi / jnp.clip(pi_xi.sum(axis=-1, keepdims=True), 1e-12, None)
    pi = pi_xi.sum(axis=0)
    pi = pi / pi.sum()
    return pi_xi, pi


def eig_scores(
    dirichlets: jnp.ndarray,   # (H, C, C)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    hard_preds: jnp.ndarray,   # (N, H) int32 argmax predictions
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
) -> jnp.ndarray:
    """Expected information gain of labeling each point. Returns (N,).

    For every point and hypothetical true class c, apply the +1-count Beta
    update to the diagonal Beta of row c of every model (the scalable
    shortcut of reference ``batch_update_beta``, ``coda/coda.py:150-168``),
    recompute P(best | row c), propagate the delta through the class mixture,
    and take the expected entropy drop under the item's class posterior
    (reference ``coda/coda.py:235-281``).
    """
    H, C, _ = dirichlets.shape
    a_cc, b_cc = dirichlet_to_beta(dirichlets)     # (H, C)
    aT, bT = a_cc.T, b_cc.T                         # (C, H)
    pbest_before = compute_pbest(aT, bT, num_points=num_points)  # (C, H)
    mixture0 = (pi_hat[:, None] * pbest_before).sum(0)           # (H,)
    h_before = entropy2(mixture0)

    class_range = jnp.arange(C, dtype=jnp.int32)

    def item_eig(args):
        pred_n, pi_xi_n = args                      # (H,) int32, (C,)
        eq = (pred_n[None, :] == class_range[:, None]).astype(aT.dtype)  # (C, H)
        a_hyp = aT + update_weight * eq
        b_hyp = bT + update_weight * (1.0 - eq)
        pbest_hyp = compute_pbest(a_hyp, b_hyp, num_points=num_points)  # (C, H)
        # only row c changed, so the mixture delta is row c's contribution
        mix_new = mixture0[None, :] + pi_hat[:, None] * (pbest_hyp - pbest_before)
        h_after = entropy2(mix_new, axis=-1)        # (C,)
        return h_before - (pi_xi_n * h_after).sum()

    return lax.map(item_eig, (hard_preds, pi_hat_xi), batch_size=chunk)


def eig_scores_factored(
    dirichlets: jnp.ndarray,   # (H, C, C)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    hard_preds: jnp.ndarray,   # (N, H) int32 argmax predictions
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
) -> jnp.ndarray:
    """EIG of labeling each point, factored for the MXU. Returns (N,).

    Same integral as :func:`eig_scores`, reorganized around one observation:
    the hypothetical +1-count update for (item n, class c) gives every model's
    row-c Beta one of only TWO parameter settings — "bumped" ``(a+w, b)`` when
    the model predicted c at n, else "unbumped" ``(a, b+w)``. So all Beta
    pdf/cdf grids are precomputed once per step at O(C*H*G) transcendentals
    (independent of N), and the per-item integral

        P(h best | c) ∝ ∫ pdf_h(x) * Π_{h'≠h} cdf_{h'}(x) dx
                      = Σ_g w_g * exp(S_{n,c,g} - logcdf_{v,h,g}) * pdf_{v,h,g}

    with ``S = Σ_h logcdf`` becomes three einsums over the model axis —
    dense fp32 matmuls on the MXU instead of per-item lgamma/cumsum. The
    max-shift of S per (n, c) replaces the reference's ±80 clamp (both only
    affect integrand tails ~1e-35 below the peak; normalization over models
    cancels the shift exactly). Everything else — grid, eps floors, trapezoid
    rule, mixture delta — matches :func:`eig_scores` / reference
    ``coda/coda.py:235-281``.
    """
    H, C, _ = dirichlets.shape
    a_cc, b_cc = dirichlet_to_beta(dirichlets)       # (H, C)
    aT, bT = a_cc.T, b_cc.T                          # (C, H)
    pbest_before = compute_pbest(aT, bT, num_points=num_points)  # (C, H)
    mixture0 = (pi_hat[:, None] * pbest_before).sum(0)           # (H,)
    h_before = entropy2(mixture0)

    x = pbest_grid(num_points)                       # (G,)
    dx = x[1] - x[0]
    # uniform-grid trapezoid weights; any constant scale cancels in the
    # per-(n,c) normalization over models, but keep the exact rule anyway
    w_trapz = jnp.full((num_points,), dx, x.dtype).at[0].set(0.5 * dx)
    w_trapz = w_trapz.at[-1].set(0.5 * dx)

    def tables(a, b):
        logpdf = beta_log_pdf(x, a[..., None], b[..., None])     # (C, H, G)
        pdf = jnp.exp(logpdf)
        cdf = cumtrapz_uniform(pdf, dx, axis=-1)
        logcdf = jnp.log(jnp.clip(cdf, _EPS, None))
        # exp(logpdf - logcdf) <= pdf_max * 1/eps-floor; cap the exponent so
        # fp32 never overflows (binds only where the integrand is ~0 anyway)
        F = jnp.exp(jnp.clip(logpdf - logcdf, None, 85.0))
        return logcdf, F

    logcdf_u, F_u = tables(aT, bT + update_weight)   # model predicted != c
    logcdf_b, F_b = tables(aT + update_weight, bT)   # model predicted c
    S0 = logcdf_u.sum(axis=1)                        # (C, G)
    dlogcdf = logcdf_b - logcdf_u                    # (C, H, G)
    dF = F_b - F_u                                   # (C, H, G)

    class_range = jnp.arange(C, dtype=jnp.int32)

    def chunk_eig(args):
        pred_b, pi_xi_b = args                       # (B, H) int32, (B, C)
        eq = (pred_b[:, None, :] == class_range[None, :, None]).astype(x.dtype)
        # S[n,c,g] = Σ_h logcdf of whichever variant model h takes at (n,c)
        S = S0[None] + jnp.einsum("bch,chg->bcg", eq, dlogcdf,
                                  precision=_PRECISION)
        S = S - S.max(axis=-1, keepdims=True)        # underflow guard
        wE = w_trapz * jnp.exp(S)                    # (B, C, G)
        t_base = jnp.einsum("bcg,chg->bch", wE, F_u, precision=_PRECISION)
        t_diff = jnp.einsum("bcg,chg->bch", wE, dF, precision=_PRECISION)
        unnorm = t_base + eq * t_diff                # (B, C, H)
        pbest_hyp = unnorm / jnp.clip(unnorm.sum(-1, keepdims=True), _EPS, None)
        # only row c changed; propagate the delta through the class mixture
        mix_new = mixture0[None, None] + pi_hat[None, :, None] * (
            pbest_hyp - pbest_before[None]
        )
        h_after = entropy2(mix_new, axis=-1)         # (B, C)
        return h_before - (pi_xi_b * h_after).sum(-1)

    N = hard_preds.shape[0]
    if chunk >= N:
        return chunk_eig((hard_preds, pi_hat_xi))

    # memory valve: scan over explicit (chunk, ·) blocks so each step is a
    # handful of dense (B,C,H)/(B,C,G) matmuls; pad the remainder
    pad = (-N) % chunk
    hp_pad = jnp.pad(hard_preds, ((0, pad), (0, 0)))
    px_pad = jnp.pad(pi_hat_xi, ((0, pad), (0, 0)))
    n_chunks = (N + pad) // chunk
    blocks = (
        hp_pad.reshape(n_chunks, chunk, -1),
        px_pad.reshape(n_chunks, chunk, -1),
    )
    out = lax.map(chunk_eig, blocks)                 # (n_chunks, chunk)
    return out.reshape(-1)[:N]


def _disagreement_mask(hard_preds: jnp.ndarray, C: int) -> jnp.ndarray:
    """Points where at least one model disagrees with the majority vote.

    The reference uses ``torch.mode`` over models (``coda/coda.py:215-219``);
    here the majority is the argmax of one-hot vote counts (identical choice:
    both pick the smallest modal class).
    """
    votes = jax.nn.one_hot(hard_preds, C, dtype=jnp.int32).sum(axis=1)  # (N, C)
    maj = jnp.argmax(votes, axis=-1)                                    # (N,)
    return (hard_preds != maj[:, None]).any(axis=-1)


def make_coda(
    preds: jnp.ndarray,
    hp: Optional[CODAHyperparams] = None,
    name: str = "coda",
) -> Selector:
    """Build the CODA selector closed over a prediction tensor."""
    hp = hp or CODAHyperparams()
    H, N, C = preds.shape
    prior_strength = 1.0 - hp.alpha
    update_strength = hp.learning_rate

    # statics (functions of preds only)
    hard_preds = preds.argmax(-1).T.astype(jnp.int32)     # (N, H)
    disagree = _disagreement_mask(hard_preds, C)          # (N,)
    ens_hard = ensemble_preds(preds).argmax(-1)           # consensus pseudo-labels
    soft_conf = create_confusion_matrices(ens_hard, preds, mode="soft")
    dirichlets0 = hp.multiplier * initialize_dirichlets(
        soft_conf, prior_strength, hp.disable_diag_prior
    )
    if hp.q == "uncertainty":
        from coda_tpu.selectors.uncertainty import uncertainty_scores
        unc_scores = uncertainty_scores(preds)            # (N,)

    def init(key):
        del key  # CODA's initialization is deterministic
        pi_xi, pi = update_pi_hat(dirichlets0, preds)
        return CODAState(
            dirichlets=dirichlets0,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=jnp.ones((N,), dtype=bool),
        )

    def _candidates(state: CODAState) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(candidate mask, may_subsample).

        Reference order (``coda/coda.py:239,215-224``): the disagreement
        filter runs first; only a *non-empty* filtered set is subsampled.
        The all-agreement fallback to the full unlabeled set is never
        subsampled.
        """
        cand0 = disagree & state.unlabeled
        empty = ~cand0.any()
        cand = jnp.where(empty, state.unlabeled, cand0)
        return cand, ~empty

    if hp.eig_mode == "factored":
        eig_fn = eig_scores_factored
    elif hp.eig_mode == "direct":
        eig_fn = eig_scores
    else:
        raise ValueError(f"unknown eig_mode {hp.eig_mode!r}")

    def _eig_select_full(state: CODAState, cand, k_tie) -> SelectResult:
        """Score every point, mask to the candidate set at argmax time."""
        scores = eig_fn(
            state.dirichlets, state.pi_hat, state.pi_hat_xi, hard_preds,
            num_points=hp.num_points, chunk=hp.eig_chunk,
        )
        idx, n_ties = masked_argmax_tiebreak(k_tie, scores, cand,
                                             rtol=_TIE_RTOL, atol=_TIE_ATOL)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=n_ties > 1,
        )

    def _eig_select_prefiltered(state: CODAState, cand, k_sub,
                                k_tie) -> SelectResult:
        """Fixed-budget random subsample of the candidates (the speed valve:
        EIG runs on prefilter_n points, not N). top-k of masked uniforms = a
        uniform random subset; when fewer than prefilter_n candidates exist,
        the invalid (masked) slots are excluded again at argmax time, so the
        pool is exactly the candidate set and no subsampling happened."""
        u = jnp.where(cand, jax.random.uniform(k_sub, (N,)), -1.0)
        _, cand_idx = jax.lax.top_k(u, hp.prefilter_n)   # (K,)
        valid = u[cand_idx] >= 0.0
        scores_sub = eig_fn(
            state.dirichlets, state.pi_hat, state.pi_hat_xi[cand_idx],
            hard_preds[cand_idx],
            num_points=hp.num_points,
            chunk=min(hp.eig_chunk, hp.prefilter_n),
        )
        local, n_ties = masked_argmax_tiebreak(
            k_tie, scores_sub, valid, rtol=_TIE_RTOL, atol=_TIE_ATOL
        )
        subsampled = cand.sum() > hp.prefilter_n
        return SelectResult(
            idx=cand_idx[local].astype(jnp.int32),
            prob=scores_sub[local],
            stochastic=(n_ties > 1) | subsampled,
        )

    def select(state: CODAState, key) -> SelectResult:
        k_sub, k_tie = jax.random.split(key)
        cand, may_subsample = _candidates(state)
        use_prefilter = hp.q == "eig" and hp.prefilter_n and hp.prefilter_n < N

        if hp.q == "eig" and not use_prefilter:
            return _eig_select_full(state, cand, k_tie)
        if use_prefilter:
            # only a non-empty *disagreement* set may be subsampled; the
            # all-agreement fallback scores every unlabeled point, exactly
            # like the reference (`_prefilter(...) or self.unlabeled_idxs`,
            # coda/coda.py:239 — the fallback never passes through the
            # random.sample branch)
            return lax.cond(
                may_subsample,
                lambda s: _eig_select_prefiltered(s, cand, k_sub, k_tie),
                lambda s: _eig_select_full(s, cand, k_tie),
                state,
            )

        # the ablation acquisitions (cheap scores) subsample via the mask
        # *before* scores are computed, so the iid probability is 1/|pool|
        # of the subsampled pool (reference computes cand first, then q_vals)
        subsampled = jnp.asarray(False)
        if hp.prefilter_n and hp.prefilter_n < N:
            u = jnp.where(cand, jax.random.uniform(k_sub, (N,)), -1.0)
            kth = jnp.sort(u)[N - hp.prefilter_n]
            take = may_subsample & (cand.sum() > hp.prefilter_n)
            cand = jnp.where(take, cand & (u >= kth), cand)
            subsampled = take

        if hp.q == "iid":
            scores = jnp.full((N,), 1.0) / jnp.clip(cand.sum(), 1, None)
        elif hp.q == "uncertainty":
            scores = unc_scores
        else:
            raise NotImplementedError(hp.q)

        idx, n_ties = masked_argmax_tiebreak(k_tie, scores, cand,
                                             rtol=_TIE_RTOL, atol=_TIE_ATOL)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=(n_ties > 1) | subsampled,
        )

    def update(state: CODAState, idx, true_class, prob) -> CODAState:
        del prob
        onehot = jax.nn.one_hot(hard_preds[idx], C, dtype=preds.dtype)  # (H, C)
        dirichlets = state.dirichlets.at[:, true_class, :].add(
            update_strength * onehot
        )
        pi_xi, pi = update_pi_hat(dirichlets, preds)
        return CODAState(
            dirichlets=dirichlets,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=state.unlabeled.at[idx].set(False),
        )

    def get_pbest(state: CODAState) -> jnp.ndarray:
        return pbest_row_mixture(state.dirichlets, state.pi_hat,
                                 num_points=hp.num_points)  # (H,)

    def best(state: CODAState, key):
        del key  # reference uses plain argmax here (coda/coda.py:346)
        return jnp.argmax(get_pbest(state)).astype(jnp.int32), jnp.asarray(False)

    return Selector(
        name=name,
        init=init,
        select=select,
        update=update,
        best=best,
        always_stochastic=False,
        hyperparams=dict(hp._asdict()),
        hyperparam_defaults=dict(CODAHyperparams()._asdict()),
        extras={"get_pbest": get_pbest, "eig_scores": eig_scores},
    )
