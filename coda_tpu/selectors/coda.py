"""CODA: consensus-driven active model selection, TPU-native.

Capability parity with the reference method (reference ``coda/coda.py:171-346``
and its kernel functions at ``:14-168``), re-architected for XLA:

  * selector state is a fixed-shape pytree (Dirichlet posteriors + masks),
    not Python lists — jit/scan/vmap-able and trivially checkpointable;
  * the EIG acquisition is a vmapped pure function over *all* N points with
    candidate masking at argmax time, chunked only as a memory valve via
    ``lax.map(..., batch_size=...)`` (the reference chunks a Python loop at
    100 items/iter, ``coda/coda.py:261``);
  * the P(best) integral's serial CDF loop is replaced by a parallel
    cumulative trapezoid (see ``coda_tpu/ops/pbest.py``);
  * the consensus prefilter (drop points where every model agrees,
    ``coda/coda.py:215-224``) becomes a static boolean mask; the optional
    ``prefilter_n`` random subsample becomes a top-k over masked uniforms.

Numeric choreography (grid endpoints, eps floors, +-80 clamps, fp32
everywhere, HIGHEST-precision einsums) follows the reference so the EIG
argmax ordering — and therefore the label-selection trace — matches.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from coda_tpu.ops.beta import dirichlet_to_beta
from coda_tpu.ops.confusion import (
    create_confusion_matrices,
    ensemble_preds,
    initialize_dirichlets,
)
from coda_tpu.ops.masked import entropy2, masked_argmax_tiebreak
from coda_tpu.ops.pbest import compute_pbest, pbest_row_mixture
from coda_tpu.selectors.protocol import Selector, SelectResult

_PRECISION = lax.Precision.HIGHEST
# reference coda/coda.py:307 uses isclose(rtol=1e-8) with torch's default
# atol=1e-8; atol dominates for tiny EIG entropy deltas
_TIE_RTOL = 1e-8
_TIE_ATOL = 1e-8


class CODAHyperparams(NamedTuple):
    prefilter_n: int = 0
    alpha: float = 0.9            # prior_strength = 1 - alpha (coda/coda.py:189)
    learning_rate: float = 0.01   # update_strength
    multiplier: float = 2.0
    disable_diag_prior: bool = False  # ablation 1
    q: str = "eig"                # acquisition: eig | iid | uncertainty (ablation 2)
    eig_chunk: int = 256          # memory valve for the EIG map
    num_points: int = 256         # P(best) integration grid


class CODAState(NamedTuple):
    dirichlets: jnp.ndarray    # (H, C, C) Dirichlet confusion posteriors
    pi_hat_xi: jnp.ndarray     # (N, C) per-item class posterior
    pi_hat: jnp.ndarray        # (C,) marginal class estimate
    unlabeled: jnp.ndarray     # (N,) bool


def update_pi_hat(
    dirichlets: jnp.ndarray, preds: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dirichlet-adjusted class posterior per item + dataset marginal.

    ``adjusted[h,n,c] = Σ_s dirichlets[h,c,s] * preds[h,n,s]`` summed over
    models (reference ``coda/coda.py:226-233``) — a batched matmul that maps
    straight onto the MXU.
    """
    adjusted = jnp.einsum("hcs,hns->hnc", dirichlets, preds, precision=_PRECISION)
    pi_xi = adjusted.sum(axis=0)
    pi_xi = pi_xi / jnp.clip(pi_xi.sum(axis=-1, keepdims=True), 1e-12, None)
    pi = pi_xi.sum(axis=0)
    pi = pi / pi.sum()
    return pi_xi, pi


def eig_scores(
    dirichlets: jnp.ndarray,   # (H, C, C)
    pi_hat: jnp.ndarray,       # (C,)
    pi_hat_xi: jnp.ndarray,    # (N, C)
    hard_preds: jnp.ndarray,   # (N, H) int32 argmax predictions
    update_weight: float = 1.0,
    num_points: int = 256,
    chunk: int = 256,
) -> jnp.ndarray:
    """Expected information gain of labeling each point. Returns (N,).

    For every point and hypothetical true class c, apply the +1-count Beta
    update to the diagonal Beta of row c of every model (the scalable
    shortcut of reference ``batch_update_beta``, ``coda/coda.py:150-168``),
    recompute P(best | row c), propagate the delta through the class mixture,
    and take the expected entropy drop under the item's class posterior
    (reference ``coda/coda.py:235-281``).
    """
    H, C, _ = dirichlets.shape
    a_cc, b_cc = dirichlet_to_beta(dirichlets)     # (H, C)
    aT, bT = a_cc.T, b_cc.T                         # (C, H)
    pbest_before = compute_pbest(aT, bT, num_points=num_points)  # (C, H)
    mixture0 = (pi_hat[:, None] * pbest_before).sum(0)           # (H,)
    h_before = entropy2(mixture0)

    class_range = jnp.arange(C, dtype=jnp.int32)

    def item_eig(args):
        pred_n, pi_xi_n = args                      # (H,) int32, (C,)
        eq = (pred_n[None, :] == class_range[:, None]).astype(aT.dtype)  # (C, H)
        a_hyp = aT + update_weight * eq
        b_hyp = bT + update_weight * (1.0 - eq)
        pbest_hyp = compute_pbest(a_hyp, b_hyp, num_points=num_points)  # (C, H)
        # only row c changed, so the mixture delta is row c's contribution
        mix_new = mixture0[None, :] + pi_hat[:, None] * (pbest_hyp - pbest_before)
        h_after = entropy2(mix_new, axis=-1)        # (C,)
        return h_before - (pi_xi_n * h_after).sum()

    return lax.map(item_eig, (hard_preds, pi_hat_xi), batch_size=chunk)


def _disagreement_mask(hard_preds: jnp.ndarray, C: int) -> jnp.ndarray:
    """Points where at least one model disagrees with the majority vote.

    The reference uses ``torch.mode`` over models (``coda/coda.py:215-219``);
    here the majority is the argmax of one-hot vote counts (identical choice:
    both pick the smallest modal class).
    """
    votes = jax.nn.one_hot(hard_preds, C, dtype=jnp.int32).sum(axis=1)  # (N, C)
    maj = jnp.argmax(votes, axis=-1)                                    # (N,)
    return (hard_preds != maj[:, None]).any(axis=-1)


def make_coda(
    preds: jnp.ndarray,
    hp: Optional[CODAHyperparams] = None,
    name: str = "coda",
) -> Selector:
    """Build the CODA selector closed over a prediction tensor."""
    hp = hp or CODAHyperparams()
    H, N, C = preds.shape
    prior_strength = 1.0 - hp.alpha
    update_strength = hp.learning_rate

    # statics (functions of preds only)
    hard_preds = preds.argmax(-1).T.astype(jnp.int32)     # (N, H)
    disagree = _disagreement_mask(hard_preds, C)          # (N,)
    ens_hard = ensemble_preds(preds).argmax(-1)           # consensus pseudo-labels
    soft_conf = create_confusion_matrices(ens_hard, preds, mode="soft")
    dirichlets0 = hp.multiplier * initialize_dirichlets(
        soft_conf, prior_strength, hp.disable_diag_prior
    )
    if hp.q == "uncertainty":
        from coda_tpu.selectors.uncertainty import uncertainty_scores
        unc_scores = uncertainty_scores(preds)            # (N,)

    def init(key):
        del key  # CODA's initialization is deterministic
        pi_xi, pi = update_pi_hat(dirichlets0, preds)
        return CODAState(
            dirichlets=dirichlets0,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=jnp.ones((N,), dtype=bool),
        )

    def _candidates(state: CODAState) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(candidate mask, may_subsample).

        Reference order (``coda/coda.py:239,215-224``): the disagreement
        filter runs first; only a *non-empty* filtered set is subsampled.
        The all-agreement fallback to the full unlabeled set is never
        subsampled.
        """
        cand0 = disagree & state.unlabeled
        empty = ~cand0.any()
        cand = jnp.where(empty, state.unlabeled, cand0)
        return cand, ~empty

    def _eig_select_full(state: CODAState, cand, k_tie) -> SelectResult:
        """Score every point, mask to the candidate set at argmax time."""
        scores = eig_scores(
            state.dirichlets, state.pi_hat, state.pi_hat_xi, hard_preds,
            num_points=hp.num_points, chunk=hp.eig_chunk,
        )
        idx, n_ties = masked_argmax_tiebreak(k_tie, scores, cand,
                                             rtol=_TIE_RTOL, atol=_TIE_ATOL)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=n_ties > 1,
        )

    def _eig_select_prefiltered(state: CODAState, cand, k_sub,
                                k_tie) -> SelectResult:
        """Fixed-budget random subsample of the candidates (the speed valve:
        EIG runs on prefilter_n points, not N). top-k of masked uniforms = a
        uniform random subset; when fewer than prefilter_n candidates exist,
        the invalid (masked) slots are excluded again at argmax time, so the
        pool is exactly the candidate set and no subsampling happened."""
        u = jnp.where(cand, jax.random.uniform(k_sub, (N,)), -1.0)
        _, cand_idx = jax.lax.top_k(u, hp.prefilter_n)   # (K,)
        valid = u[cand_idx] >= 0.0
        scores_sub = eig_scores(
            state.dirichlets, state.pi_hat, state.pi_hat_xi[cand_idx],
            hard_preds[cand_idx],
            num_points=hp.num_points,
            chunk=min(hp.eig_chunk, hp.prefilter_n),
        )
        local, n_ties = masked_argmax_tiebreak(
            k_tie, scores_sub, valid, rtol=_TIE_RTOL, atol=_TIE_ATOL
        )
        subsampled = cand.sum() > hp.prefilter_n
        return SelectResult(
            idx=cand_idx[local].astype(jnp.int32),
            prob=scores_sub[local],
            stochastic=(n_ties > 1) | subsampled,
        )

    def select(state: CODAState, key) -> SelectResult:
        k_sub, k_tie = jax.random.split(key)
        cand, may_subsample = _candidates(state)
        use_prefilter = hp.q == "eig" and hp.prefilter_n and hp.prefilter_n < N

        if hp.q == "eig" and not use_prefilter:
            return _eig_select_full(state, cand, k_tie)
        if use_prefilter:
            # only a non-empty *disagreement* set may be subsampled; the
            # all-agreement fallback scores every unlabeled point, exactly
            # like the reference (`_prefilter(...) or self.unlabeled_idxs`,
            # coda/coda.py:239 — the fallback never passes through the
            # random.sample branch)
            return lax.cond(
                may_subsample,
                lambda s: _eig_select_prefiltered(s, cand, k_sub, k_tie),
                lambda s: _eig_select_full(s, cand, k_tie),
                state,
            )

        # the ablation acquisitions (cheap scores) subsample via the mask
        # *before* scores are computed, so the iid probability is 1/|pool|
        # of the subsampled pool (reference computes cand first, then q_vals)
        subsampled = jnp.asarray(False)
        if hp.prefilter_n and hp.prefilter_n < N:
            u = jnp.where(cand, jax.random.uniform(k_sub, (N,)), -1.0)
            kth = jnp.sort(u)[N - hp.prefilter_n]
            take = may_subsample & (cand.sum() > hp.prefilter_n)
            cand = jnp.where(take, cand & (u >= kth), cand)
            subsampled = take

        if hp.q == "iid":
            scores = jnp.full((N,), 1.0) / jnp.clip(cand.sum(), 1, None)
        elif hp.q == "uncertainty":
            scores = unc_scores
        else:
            raise NotImplementedError(hp.q)

        idx, n_ties = masked_argmax_tiebreak(k_tie, scores, cand,
                                             rtol=_TIE_RTOL, atol=_TIE_ATOL)
        return SelectResult(
            idx=idx.astype(jnp.int32),
            prob=scores[idx],
            stochastic=(n_ties > 1) | subsampled,
        )

    def update(state: CODAState, idx, true_class, prob) -> CODAState:
        del prob
        onehot = jax.nn.one_hot(hard_preds[idx], C, dtype=preds.dtype)  # (H, C)
        dirichlets = state.dirichlets.at[:, true_class, :].add(
            update_strength * onehot
        )
        pi_xi, pi = update_pi_hat(dirichlets, preds)
        return CODAState(
            dirichlets=dirichlets,
            pi_hat_xi=pi_xi,
            pi_hat=pi,
            unlabeled=state.unlabeled.at[idx].set(False),
        )

    def get_pbest(state: CODAState) -> jnp.ndarray:
        return pbest_row_mixture(state.dirichlets, state.pi_hat,
                                 num_points=hp.num_points)  # (H,)

    def best(state: CODAState, key):
        del key  # reference uses plain argmax here (coda/coda.py:346)
        return jnp.argmax(get_pbest(state)).astype(jnp.int32), jnp.asarray(False)

    return Selector(
        name=name,
        init=init,
        select=select,
        update=update,
        best=best,
        always_stochastic=False,
        hyperparams=dict(hp._asdict()),
        extras={"get_pbest": get_pbest, "eig_scores": eig_scores},
    )
